"""Preempt -> requeue -> resume of a BATCHED ensemble campaign
(ensemble.replica_batch) under the campaign server.

Batched campaigns checkpoint per replica-batch into their own
rotation series (``<save>.b<k>.t<ns>`` — batches restart sim time at
0, so a shared base would cross-prune), and a resume replays the
completed batches fresh (pure functions => bit-identical) before
loading the interrupted batch from its stamped entry. The drill:
the server preempts a batched campaign for a higher-priority
arrival, requeues it with the batch-stamped resume checkpoint, and
the resumed campaign's per-replica signatures bit-match an
uninterrupted standalone run.
"""

import json
import os
import time

import pytest

from shadow_tpu.config import load_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.serve.server import CampaignServer, submit

# drill-scale: two full campaigns plus a preempt/resume cycle — rides
# with the slow suite (CI's full-matrix tests job still runs it)
pytestmark = pytest.mark.slow

ENSEMBLE_YAML = """
general:
  stop_time: 800ms
  seed: 9
  heartbeat_interval: 200ms
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
ensemble:
  replicas: 4
  replica_batch: 2
  vary:
    seed: [9, 11, 13, 15]
hosts:
  left:
    quantity: 3
    processes:
    - {path: model:phold, args: msgload=2, start_time: 10ms}
  right:
    quantity: 3
    processes:
    - {path: model:phold, args: msgload=2, start_time: 10ms}
"""

PLAIN_YAML = ENSEMBLE_YAML.replace(
    "ensemble:\n  replicas: 4\n  replica_batch: 2\n  vary:\n"
    "    seed: [9, 11, 13, 15]\n", "")


def ensemble_sig(stats):
    return [[e.get("host_checksums_sha256", ""),
             int(e["events_executed"]), int(e["packets_sent"]),
             int(e["packets_dropped"]), int(e["packets_delivered"])]
            for e in stats.ensemble["replicas"]]


def drive(srv, timeout_s=300, until=None):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        busy = srv.tick()
        if until is not None:
            if until():
                return
        elif not busy:
            return
        time.sleep(0.005)
    raise AssertionError("server drive timed out")


def test_batched_ensemble_preempt_requeue_resume_bit_identical(
        tmp_path):
    ens_cfg = tmp_path / "ensemble.yaml"
    ens_cfg.write_text(ENSEMBLE_YAML)
    plain_cfg = tmp_path / "plain.yaml"
    plain_cfg.write_text(PLAIN_YAML)

    # the uninterrupted reference: same batched campaign, standalone
    cfg = load_config(str(ens_cfg))
    cfg.general.data_directory = str(tmp_path / "ref.data")
    cfg.experimental.artifacts_dir = str(tmp_path / "ref_artifacts")
    stats = Controller(cfg).run()
    assert stats.ok
    ref = ensemble_sig(stats)

    spool = str(tmp_path / "spool")
    submit(spool, str(ens_cfg), priority=0)
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    state = {"submitted": False}

    def inject_high_priority():
        # the urgent (plain) campaign arrives while the batched one
        # is mid-flight — its guard exists once run() starts
        if not state["submitted"] and srv._slot is not None:
            runner = srv._runner_of(srv._slot)
            if runner is not None and getattr(runner, "guard",
                                              None) is not None:
                submit(spool, str(plain_cfg), priority=9)
                state["submitted"] = True
        return state["submitted"]

    drive(srv, until=inject_high_priority)
    drive(srv)
    srv._shutdown()

    with open(os.path.join(spool, "campaigns", "c0000",
                           "RESULT.json"), encoding="utf-8") as f:
        res = json.load(f)
    assert res["state"] == "DONE"
    assert res["preemptions"] == 1 and res["attempts"] == 2
    # the drain saved a BATCH rotation entry and the requeue carried
    # it — the resumed batched campaign bit-matches the reference
    cdir = os.path.join(spool, "campaigns", "c0000")
    assert any(".b" in n and ".t" in n for n in os.listdir(cdir)
               if n.startswith("ck.npz"))
    with open(os.path.join(spool, "journal.jsonl"),
              encoding="utf-8") as f:
        rows = [json.loads(line) for line in f if line.strip()]
    pre = [r for r in rows if r.get("cid") == "c0000"
           and r.get("state") == "PREEMPTED"]
    assert pre and ".b" in pre[0]["resume_path"]
    assert res["signature"] == ref
