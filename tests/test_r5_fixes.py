"""Regression tests for ADVICE r5 (high): unix-pair vector writes.

`_upair_write` commits bytes to the peer's buffer as space appears and
parks its progress (`upair_done`) before raising Blocked. The
sendmsg/writev iov loops catch that Blocked after earlier iovs made
progress and return a short count — which used to EXCLUDE the bytes
the interrupted segment had already committed, so the application
would resend bytes the peer had already received (duplicates on the
stream). The loops now fold the parked progress into the short return.

Driven at the syscall-handler layer with a fake process/memory: the
managed-process e2e harness needs real clone/ptrace support this test
must not depend on.
"""

import struct

import pytest

from shadow_tpu.host.descriptors import UnixPairDesc
from shadow_tpu.host.syscalls import Blocked, SyscallHandler

CAP = UnixPairDesc.CAPACITY


class FlatMem:
    """ProcessMemory stand-in: one flat bytearray address space."""

    def __init__(self, size: int = 1 << 20):
        self.buf = bytearray(size)

    def read(self, addr: int, n: int) -> bytes:
        return bytes(self.buf[addr:addr + n])

    def write(self, addr: int, data: bytes) -> None:
        self.buf[addr:addr + len(data)] = data


class FakeProcess:
    def __init__(self):
        self.mem = FlatMem()
        self.syscall_state = {}
        self._fds = {}
        self.table = self

    def get(self, fd):                   # descriptor-table duck type
        return self._fds.get(fd)


class Ctx:
    now = 0


FD = 1000           # >= VFD_BASE so _no_desc never hands it native
DATA = 0x1000       # payload bytes live here in FlatMem
IOV = 0x8000        # struct iovec[2]
MSG = 0x9000        # struct msghdr


def _setup(space_left: int, nonblock: bool = False):
    """A handler whose fd FD is one end of a stream pair with exactly
    `space_left` bytes of room in the peer's inbox, and a 140-byte
    pattern split into two iovs [60, 80] staged in memory."""
    p = FakeProcess()
    h = SyscallHandler(p)
    a, b = UnixPairDesc.make_pair(dgram=False)
    a.nonblock = nonblock
    p._fds[FD] = a
    b.rbuf += bytes(CAP - space_left)    # prefill: zeros, drained first
    pattern = bytes((i * 131 + 7) & 0xFF for i in range(140))
    p.mem.write(DATA, pattern)
    p.mem.write(IOV, struct.pack("<QQQQ", DATA, 60, DATA + 60, 80))
    # msghdr: name/namelen 0, iov -> IOV, iovlen 2, rest 0
    p.mem.write(MSG, struct.pack("<QQQQQQQ", 0, 0, IOV, 2, 0, 0, 0))
    return h, a, b, pattern


def _stream_tail(b, prefill: int) -> bytes:
    return bytes(b.rbuf[prefill:])


@pytest.mark.parametrize("call", ["sendmsg", "writev"])
def test_upair_vector_write_counts_committed_bytes(call):
    # space for 100 bytes: iov[0] (60) fits whole, iov[1] commits 40
    # and then blocks — the short return must say 100, matching what
    # the peer actually received
    h, a, b, pattern = _setup(space_left=100)
    if call == "sendmsg":
        r = h.sys_sendmsg(Ctx(), (FD, MSG, 0))
    else:
        r = h.sys_writev(Ctx(), (FD, IOV, 2))
    assert r == 100
    assert len(b.rbuf) == CAP
    assert _stream_tail(b, CAP - 100) == pattern[:100]
    # the syscall replied: no parked progress may leak into the next
    assert h.state == {}


def test_upair_first_iov_block_still_parks_and_resumes():
    # space for 40: iov[0] commits 40 of its 60 bytes then blocks with
    # nothing yet counted — the syscall must park (restart semantics)
    # and the replay must resume, not repeat, the committed bytes
    h, a, b, pattern = _setup(space_left=40)
    with pytest.raises(Blocked):
        h.sys_sendmsg(Ctx(), (FD, MSG, 0))
    assert h.state.get("upair_done") == 40
    got = _stream_tail(b, CAP - 40)
    del b.rbuf[:]                        # the peer drains everything
    r = h.sys_sendmsg(Ctx(), (FD, MSG, 0))   # parked syscall replays
    assert r == 140
    got += bytes(b.rbuf)
    assert got == pattern                # no duplicate, no hole
    assert h.state == {}


def test_upair_nonblocking_vector_write_unchanged():
    # nonblocking path already folded progress (returns done) — pin it
    h, a, b, pattern = _setup(space_left=100, nonblock=True)
    r = h.sys_sendmsg(Ctx(), (FD, MSG, 0))
    assert r == 100
    assert _stream_tail(b, CAP - 100) == pattern[:100]
    assert h.state == {}
