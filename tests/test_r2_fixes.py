"""Regression tests for the round-2 semantic fixes, each designed to
fail on the pre-fix code:

* recvmmsg: MSG_WAITFORONE drain, the consult-timeout-only-after-a-
  datagram kernel quirk, and the expired-deadline restart path
  (host/syscalls.py sys_recvmmsg).
* NULL-offset sendfile advances the shared file description; explicit
  offset does not (host/syscalls.py sys_sendfile).
* RTO on a fully-SACKed flight reneges the SACK state and retransmits
  (RFC 2018 §8; host/tcp.py on_timer).
* joiner-vs-exit stress on the kernel-cleared thread-death guard
  (host/process.py _finish_thread_exit).
"""

import os
import subprocess

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.host.tcp import TcpFlags, TcpSocket, TcpState
from shadow_tpu.routing.packet import Packet

PLUGIN_DIR = os.path.join(os.path.dirname(__file__), "plugins")

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.0 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
]"""


def _indent(text: str, n: int) -> str:
    return "\n".join(" " * n + line for line in text.splitlines())


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("plugins")
    built = {}
    for name in ("recvmmsg_check", "udp_burst", "sendfile_offset_check",
                 "thread_exit_stress", "tcp_server"):
        exe = out / name
        subprocess.run(
            ["cc", "-O1", "-pthread", "-o", str(exe),
             os.path.join(PLUGIN_DIR, f"{name}.c")],
            check=True, capture_output=True)
        built[name] = str(exe)
    return built


def run_sim(hosts_yaml: str, data: str, stop: str = "30s"):
    cfg = load_config_str(f"""
general:
  stop_time: {stop}
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
{hosts_yaml}
""")
    c = Controller(cfg)
    return c.run()


def stdout_of(data: str, host: str, exe: str) -> str:
    d = os.path.join(data, "hosts", host)
    for f in sorted(os.listdir(d)):
        if f.startswith(exe) and f.endswith(".stdout"):
            with open(os.path.join(d, f)) as fh:
                return fh.read()
    raise FileNotFoundError(f"no stdout for {exe} in {d}")


# ---------------------------------------------------------------------
# recvmmsg
# ---------------------------------------------------------------------
def test_recvmmsg_waitforone_timeout_and_restart(bins, tmp_path):
    """Receiver on node 0 (starts 1s), scripted burst sender on node 1
    (starts 1.5s; 25 ms one-way). Deterministic sim clocks pin each
    scenario's return count AND return time:
      a) WAITFORONE at 1.7 with d1+d2 queued since 1.525 -> drains
         both instantly (n=2, dt=0)
      b) 100 ms timeout expires while empty; d3 arrives 1.825 -> the
         timeout is only consulted after a datagram, so n=1 at arrival
         (dt=0.125 from the 1.7 call time)
      c) 600 ms window, d4 arrives mid-window at 2.325 -> n=1 at the
         2.425 deadline (exercises the Blocked-with-deadline restart)
    """
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  recv:
    network_node_id: 0
    processes:
    - path: {bins['recvmmsg_check']}
      args: 9000
      start_time: 1s
  send:
    network_node_id: 1
    processes:
    - path: {bins['udp_burst']}
      args: 11.0.0.1 9000
      start_time: 1.5s
""", data, stop="10s")
    assert stats.ok
    out = stdout_of(data, "recv", "recvmmsg_check").splitlines()
    assert out[0] == "a n=2 dt=0.000"
    assert out[1] == "b n=1 dt=0.125"
    assert out[2] == "c n=1 dt=0.600"


# ---------------------------------------------------------------------
# sendfile
# ---------------------------------------------------------------------
def test_sendfile_null_offset_advances_fd(bins, tmp_path):
    """After sendfile(sock, f, NULL, 4096) the same fd's read must see
    bytes 4096.. (shared file description advanced); an explicit-offset
    sendfile must leave the fd position alone."""
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  server:
    network_node_id: 0
    processes:
    - path: {bins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {bins['sendfile_offset_check']}
      args: 11.0.0.1 8080
      start_time: 2s
""", data)
    assert stats.ok
    out = stdout_of(data, "client", "sendfile_offset_check").splitlines()
    assert out[0] == "sf1 n=4096"
    assert out[1] == "pos after null-offset sendfile: 4096"
    # bytes at offset 4096: 4096&0xff=0, then 1 2 3
    assert out[2] == "read n=4 bytes 0 1 2 3"
    assert out[3] == "sf2 n=1024 off=1024 moved=0"


# ---------------------------------------------------------------------
# RTO on a fully-SACKed flight
# ---------------------------------------------------------------------
class _FakeIface:
    def wants_send(self, sock, now):
        pass


class _FakeNet:
    """Minimal HostNetStack stand-in for driving TcpSocket directly."""

    def __init__(self):
        self.tcp_segments_sent = 0
        self.tcp_segments_retransmitted = 0
        self.timers = []
        self.ctx = None
        self._iface = _FakeIface()

    def new_conn_id(self, sock):
        return 1

    def register(self, sock):
        pass

    def unregister(self, sock):
        pass

    def interface_for(self, dst):
        return self._iface

    def new_packet(self, dst_host, protocol, size, src_port=0,
                   dst_port=0, payload=None):
        return Packet(src_host=0, packet_id=0, dst_host=dst_host,
                      protocol=protocol, size=size, src_port=src_port,
                      dst_port=dst_port, payload=payload)

    def schedule_tcp_timer(self, conn_id, gen, when):
        self.timers.append((when, conn_id, gen))


def test_rto_on_fully_sacked_flight_reneges_and_retransmits():
    """RFC 2018 §8: after an RTO the sender must discard SACK state.
    Pre-fix, a flight whose every segment was SACKed (but never
    cumulatively ACKed — renege) left _retransmit_first with no
    candidate: no retransmission, no progress. Post-fix the tally is
    cleared and the first segment goes out again."""
    net = _FakeNet()
    s = TcpSocket(net, 1234)
    s.state = TcpState.ESTABLISHED
    s.peer = (1, 80)
    # a 3-segment flight, all selectively acked, none cumulatively
    for seq, size in ((0, 1000), (1000, 1000), (2000, 1000)):
        s.retx.append([seq, size, 1, 0, int(TcpFlags.ACK)])
        s.tally.mark_sacked(seq, seq + size)
    assert s.tally.is_sacked(0, 3000)
    s._rto_armed = True
    gen = s._timer_gen
    before = s.segments_retransmitted
    s.on_timer(1_000_000, gen)
    assert s.tally.sacked == []                 # renege: SACK discarded
    assert s.segments_retransmitted == before + 1
    assert s._rto_armed                          # timer re-armed


def test_rto_without_sack_still_retransmits():
    net = _FakeNet()
    s = TcpSocket(net, 1234)
    s.state = TcpState.ESTABLISHED
    s.peer = (1, 80)
    s.retx.append([0, 1000, 1, 0, int(TcpFlags.ACK)])
    s._rto_armed = True
    s.on_timer(1_000_000, s._timer_gen)
    assert s.segments_retransmitted == 1


# ---------------------------------------------------------------------
# joiner-vs-exit stress
# ---------------------------------------------------------------------
def test_thread_exit_join_stress(bins, tmp_path):
    """64 create/exit/join cycles, each reusing the previous thread's
    stack: any early joiner wake-up (before the kernel-cleared death
    guard) corrupts a live stack. acc = sum(3i+1, i<64) = 6112."""
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  alice:
    network_node_id: 0
    processes:
    - path: {bins['thread_exit_stress']}
      args: 64
      start_time: 1s
""", data)
    assert stats.ok
    assert stdout_of(data, "alice", "thread_exit_stress") == "acc 6112\n"
