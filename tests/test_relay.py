"""The flagship real-application demo (VERDICT r3 #7): a 3-hop relay
circuit of REAL C processes forwarding through the emulated TCP stack
— the honest analogue of the reference's real-tor flagship
(/root/reference/src/test/tor) — run under hybrid (device network
judgments) and bit-compared against the pure-CPU oracle.
"""


from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

from test_managed import (  # noqa: F401  (fixture re-export)
    GML,
    _indent,
    plugins,
    read_stdout,
)

NBYTES = 60_000
SUM_TAG = "sum"


def _circuit_cfg(policy: str, data: str, bins: dict) -> str:
    # client(n0) -> relay1(n1) -> relay2(n0) -> relay3(n1) -> server(n0)
    # (alternating vertices so every hop crosses the lossy-free edge)
    gml = _indent(GML, 6)
    return f"""
general:
  stop_time: 120s
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{gml}
experimental:
  scheduler_policy: {policy}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: {bins['tcp_server']}, args: 8080, start_time: 1s}}
  relay1:
    network_node_id: 1
    processes:
    - {{path: {bins['relay']}, args: 9001, start_time: 1s}}
  relay2:
    network_node_id: 0
    processes:
    - {{path: {bins['relay']}, args: 9002, start_time: 1s}}
  relay3:
    network_node_id: 1
    processes:
    - {{path: {bins['relay']}, args: 9003, start_time: 1s}}
  client:
    network_node_id: 0
    processes:
    - {{path: {bins['onion_client']},
       args: 11.0.0.2 9001 {NBYTES} 11.0.0.3 9002 11.0.0.4 9003 11.0.0.1 8080,
       start_time: 2s}}
"""


def _run(policy: str, data: str, bins: dict):
    cfg = load_config_str(_circuit_cfg(policy, data, bins))
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok, policy
    outs = {}
    for h in ("server", "relay1", "relay2", "relay3", "client"):
        exe = {"server": "tcp_server", "client": "onion_client"}.get(
            h, "relay")
        outs[h] = read_stdout(data, h, exe)
    chks = [(h.name, h.trace_checksum, h.packets_sent,
             h.packets_dropped) for h in c.sim.hosts]
    return c, outs, chks


def test_relay_circuit_hybrid_matches_cpu_oracle(plugins, tmp_path):
    """The full circuit completes under hybrid (tpu->hybrid fallback:
    real processes + batched device judgments) with stdout AND trace
    checksums identical to the serial CPU oracle; every relay
    forwarded exactly the payload + the remaining headers."""
    results = {}
    for policy in ("serial", "tpu"):
        data = str(tmp_path / policy / "shadow.data")
        c, outs, chks = _run(policy, data, plugins)
        if policy == "tpu":
            assert c.manager is not None          # hybrid, not twin
            j = c.manager.net_judge
            assert j is not None
            # small rounds ride the CPU side of the adaptive split
            assert j.packets + j.cpu_packets > 0
        results[policy] = (outs, chks)

    serial, tpu = results["serial"], results["tpu"]
    assert serial[0] == tpu[0]
    assert serial[1] == tpu[1]

    outs = tpu[0]
    # the sink received the exact payload the client checksummed
    client_sum = [ln for ln in outs["client"].splitlines()
                  if SUM_TAG in ln][0].split()
    server_sum = [ln for ln in outs["server"].splitlines()
                  if SUM_TAG in ln][0].split()
    assert client_sum[1] == server_sum[1] == str(NBYTES)
    assert client_sum[4] == server_sum[4]
    # each relay forwarded payload + the headers it did NOT peel
    hdr = len("11.0.0.3 9002\n")
    assert f"forwarded {NBYTES + 2 * hdr}" in outs["relay1"]
    assert f"forwarded {NBYTES + hdr}" in outs["relay2"]
    assert f"forwarded {NBYTES}" in outs["relay3"]


def test_relay_circuit_deterministic(plugins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        _, o, chk = _run("tpu", data, plugins)
        outs.append((o, chk))
    assert outs[0] == outs[1]
