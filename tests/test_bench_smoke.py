"""BENCH_SMOKE=1 bench.py as a slow-marked test: bench regressions
(like the r5 zero-division on a zero-packet rung) must fail here
before a relay window is spent discovering them. CPU platform, tiny
ladder — this validates the bench MECHANICS (ladder, ratio guards,
JSON contract, occupancy record), not the numbers."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_emits_valid_json(tmp_path):
    env = dict(os.environ,
               BENCH_SMOKE="1",
               JAX_PLATFORMS="cpu",
               SHADOW_TPU_OCC_DIR=str(tmp_path))
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=900)
    # the contract: exactly one JSON line on stdout, always
    lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, p.stdout + p.stderr
    result = json.loads(lines[0])
    assert result["metric"] == "packets_routed_per_sec_per_chip"
    assert p.returncode == 0, (result, p.stderr[-2000:])
    assert "error" not in result, result
    assert result["value"] > 0
    assert result["ladder"]["tgen_100"]["speedup"] > 0
    # a non-fallback run is stamped so, explicitly
    assert result["fallback"] is False
    # the multichip rung ran on the virtual 8-device mesh (conftest's
    # XLA_FLAGS reach the subprocess) and recorded ICI volume next to
    # throughput
    mc = result["multichip"]
    assert "error" not in mc, mc
    if "skipped" not in mc:
        assert mc["n_chips"] > 1
        assert mc["pkts_per_s"] > 0
        assert mc["ici_rows_per_flush"] > 0
        assert mc["ici_rows_per_round"] > 0
        assert mc["exchange"] in ("all_to_all", "all_gather",
                                  "two_phase")
    # the topology rung stamped the hierarchical-vs-dense table cost
    # (1M point skipped under smoke) and met the reduction floor
    topo = result["topology"]
    assert "error" not in topo, topo
    pts = {pt["label"]: pt for pt in topo["points"]}
    assert pts["100k"]["reduction"] >= 100
    assert pts["1k"]["hier_table_bytes"] < pts["1k"]["dense_table_bytes"]
    assert "1M" not in pts
    # the run's measured occupancy landed for tune_10k.py to reuse
    occ_path = result["occupancy_record"]
    with open(occ_path) as f:
        occ = json.load(f)
    assert occ["measured"]["outbox_rows_max"] > 0
    assert occ["workload"]["n_hosts"] == 100


@pytest.mark.slow
def test_bench_cpu_fallback_ladder_branch(tmp_path):
    """The cpu-fallback ladder branch — the untested path that
    produced the BENCH_r05 0.0 (the 2.0s tgen_1000 slice ended exactly
    at the clients' 2s start_time, dividing by zero). Driven directly
    via BENCH_FORCE_FALLBACK (not the JAX_PLATFORMS=cpu non-fallback
    path the smoke test above pins): the record must carry nonzero
    numbers plus the NAMED tpu-unavailable diagnostic — never a bare
    ZeroDivisionError."""
    env = dict(os.environ,
               BENCH_SMOKE="1",
               BENCH_FORCE_FALLBACK="1",
               SHADOW_TPU_OCC_DIR=str(tmp_path))
    env.pop("JAX_PLATFORMS", None)     # the fallback forces cpu itself
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=900)
    lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, p.stdout + p.stderr
    result = json.loads(lines[0])
    # fallback exits nonzero BY CONTRACT, with the named diagnostic —
    # a CPU-vs-CPU ratio must never masquerade as a device benchmark
    assert p.returncode == 1, (result, p.stderr[-2000:])
    assert "tpu backend unavailable" in result.get("error", ""), result
    assert "division" not in result.get("error", ""), result
    # ... but the record still carries real numbers from the slice
    assert result["value"] > 0, (result, p.stderr[-2000:])
    assert result["platform"] == "cpu"
    assert result["fallback"] is True      # the explicit stamp
    assert result["vs_baseline"] is None
    assert result["ladder"]["tgen_100"]["speedup"] > 0
