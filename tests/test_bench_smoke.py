"""BENCH_SMOKE=1 bench.py as a slow-marked test: bench regressions
(like the r5 zero-division on a zero-packet rung) must fail here
before a relay window is spent discovering them. CPU platform, tiny
ladder — this validates the bench MECHANICS (ladder, ratio guards,
JSON contract, occupancy record), not the numbers."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_emits_valid_json(tmp_path):
    env = dict(os.environ,
               BENCH_SMOKE="1",
               JAX_PLATFORMS="cpu",
               SHADOW_TPU_OCC_DIR=str(tmp_path))
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=900)
    # the contract: exactly one JSON line on stdout, always
    lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, p.stdout + p.stderr
    result = json.loads(lines[0])
    assert result["metric"] == "packets_routed_per_sec_per_chip"
    assert p.returncode == 0, (result, p.stderr[-2000:])
    assert "error" not in result, result
    assert result["value"] > 0
    assert result["ladder"]["tgen_100"]["speedup"] > 0
    # the run's measured occupancy landed for tune_10k.py to reuse
    occ_path = result["occupancy_record"]
    with open(occ_path) as f:
        occ = json.load(f)
    assert occ["measured"]["outbox_rows_max"] > 0
    assert occ["workload"]["n_hosts"] == 100
