"""Regression tests for the round-5 advisor's robustness findings.

ADVICE r5 medium (host/syscalls.py): ``mkfifo()`` + blocking
``open(O_RDONLY)`` used to wedge the simulator thread in a host-side
blocking ``os.open`` — the writer process could never be scheduled to
unblock it, a whole-simulation deadlock. FIFOs now open host-side with
O_NONBLOCK always and blocking-open semantics are emulated through the
``Blocked``/readiness machinery (like the socket paths), so the
previously-deadlocking pattern completes.

Also: the wall-clock round watchdog (core/manager.py RoundWatchdog) —
no scheduling progress for a configured interval dumps per-host state
and aborts with a diagnostic instead of hanging forever.

Driven at the syscall-handler layer with a fake process/memory (the
test_r5_fixes.py pattern): the managed-process e2e harness needs real
clone/ptrace support these tests must not depend on.
"""

import os
import stat
import threading
import time

import pytest

from shadow_tpu.host.descriptors import VFD_BASE
from shadow_tpu.host.syscalls import Blocked, SyscallHandler


class FlatMem:
    """ProcessMemory stand-in: one flat bytearray address space."""

    def __init__(self, size: int = 1 << 20):
        self.buf = bytearray(size)

    def read(self, addr: int, n: int) -> bytes:
        return bytes(self.buf[addr:addr + n])

    def read_cstr(self, addr: int) -> bytes:
        end = self.buf.index(0, addr)
        return bytes(self.buf[addr:end])

    def write(self, addr: int, data: bytes) -> None:
        self.buf[addr:addr + len(data)] = data


class MiniTable:
    def __init__(self):
        self._slots = {}
        self._next = VFD_BASE
        self.cloexec = set()

    def alloc(self, d) -> int:
        fd = self._next
        self._next += 1
        self._slots[fd] = d
        return fd

    def get(self, fd):
        return self._slots.get(fd)

    def has_room(self) -> bool:
        return True


class HostStub:
    def __init__(self, name):
        self.name = name


class FakeProcess:
    def __init__(self, host, runtime):
        self.mem = FlatMem()
        self.syscall_state = {}
        self.table = MiniTable()
        self.host = host
        self.runtime = runtime
        self.alive = True


class Runtime:
    def __init__(self, data_dir):
        self.data_dir = data_dir


class Ctx:
    def __init__(self):
        self.now = 0


PATH_PTR = 0x1000
BUF = 0x4000


@pytest.fixture
def fifo_world(tmp_path):
    """Two fake processes on ONE host whose data dir holds a fresh
    FIFO; both handlers see the same per-host FIFO registry."""
    host_dir = tmp_path / "hosts" / "h0"
    host_dir.mkdir(parents=True)
    host = HostStub("h0")
    rt = Runtime(str(tmp_path))
    pa, pb = FakeProcess(host, rt), FakeProcess(host, rt)
    ha, hb = SyscallHandler(pa), SyscallHandler(pb)
    for h in (ha, hb):
        h.p.mem.write(PATH_PTR, b"fifo0\x00")
    return ha, hb, str(host_dir / "fifo0")


def _open(h, ctx, flags):
    return h.sys_open(ctx, (PATH_PTR, flags, 0o644))


O_RDONLY, O_WRONLY, O_RDWR, O_NONBLOCK = 0, 1, 2, 0x800


def test_mkfifo_then_blocking_open_no_longer_deadlocks(fifo_world):
    """The exact ADVICE r5 pattern: mknod(S_IFIFO) then a blocking
    open(O_RDONLY). The old passthrough would block the calling
    (simulator) thread inside os.open forever; the fix parks the
    syscall via Blocked instead, and the open completes once a writer
    arrives."""
    ha, hb, fifo = fifo_world
    ctx = Ctx()
    # create the FIFO through the emulated mknod (S_IFIFO | 0644)
    assert ha.sys_mknod(ctx, (PATH_PTR, 0o010644, 0)) == 0
    assert stat.S_ISFIFO(os.stat(fifo).st_mode)

    # reader: blocking open parks (restart semantics), never wedges
    with pytest.raises(Blocked) as bi:
        _open(ha, ctx, O_RDONLY)
    assert bi.value.deadline is not None and bi.value.deadline > ctx.now

    # writer: blocking open also parks (no reader admitted yet)
    with pytest.raises(Blocked):
        _open(hb, ctx, O_WRONLY)

    # reader's retry sees the pending writer and completes ...
    ctx.now += 2_000_000
    rfd = _open(ha, ctx, O_RDONLY)
    assert rfd >= VFD_BASE
    # ... and the writer's retry then finds a live reader
    wfd = _open(hb, ctx, O_WRONLY)
    assert wfd >= VFD_BASE

    # data flows through the emulated fds
    hb.p.mem.write(BUF, b"ping")
    assert hb.sys_write(ctx, (wfd, BUF, 4)) == 4
    assert ha.sys_read(ctx, (rfd, BUF + 64, 4)) == 4
    assert ha.p.mem.read(BUF + 64, 4) == b"ping"

    # parked-open bookkeeping fully drained
    assert ha.p.syscall_state == {} and hb.p.syscall_state == {}


def test_fifo_nonblocking_writer_enxio(fifo_world):
    ha, hb, fifo = fifo_world
    os.mkfifo(fifo)
    ctx = Ctx()
    ENXIO = 6
    assert _open(hb, ctx, O_WRONLY | O_NONBLOCK) == -ENXIO
    # a nonblocking reader succeeds with no writer at all
    rfd = _open(ha, ctx, O_RDONLY | O_NONBLOCK)
    assert rfd >= VFD_BASE
    # and now the nonblocking writer finds its reader
    assert _open(hb, ctx, O_WRONLY | O_NONBLOCK) >= VFD_BASE


def test_fifo_rdwr_never_blocks(fifo_world):
    ha, _, fifo = fifo_world
    os.mkfifo(fifo)
    assert _open(ha, Ctx(), O_RDWR) >= VFD_BASE


def test_fifo_blocking_read_parks_until_data(fifo_world):
    ha, hb, fifo = fifo_world
    os.mkfifo(fifo)
    ctx = Ctx()
    rfd = _open(ha, ctx, O_RDONLY | O_NONBLOCK)
    # flip the app-visible fd to blocking (as fcntl F_SETFL would)
    ha.p.table.get(rfd).nonblock = False
    wfd = _open(hb, ctx, O_WRONLY)
    # no data yet: a blocking virtual read parks on the poll deadline
    # instead of surfacing the host-side EAGAIN
    with pytest.raises(Blocked):
        ha.sys_read(ctx, (rfd, BUF, 16))
    hb.p.mem.write(BUF, b"x")
    assert hb.sys_write(ctx, (wfd, BUF, 1)) == 1
    assert ha.sys_read(ctx, (rfd, BUF + 32, 16)) == 1


def test_fifo_open_flags_keep_app_view(fifo_world):
    """The host-side fd is always O_NONBLOCK (the deadlock fix), but
    the APP's descriptor must report the flags it asked for."""
    ha, hb, fifo = fifo_world
    os.mkfifo(fifo)
    ctx = Ctx()
    rfd = _open(ha, ctx, O_RDONLY | O_NONBLOCK)
    d = ha.p.table.get(rfd)
    assert d.nonblock and d.is_fifo
    wfd = _open(hb, ctx, O_WRONLY)
    dw = hb.p.table.get(wfd)
    assert not dw.nonblock and dw.is_fifo
    # the real kernel-side fd really is nonblocking (the wedge is
    # structurally impossible now)
    assert os.get_blocking(dw.osfd) is False


def test_fifo_second_reader_blocks_without_writer(fifo_world):
    """fifo(7): a read-only open blocks until a WRITER end exists —
    other readers are irrelevant, so a held reader fd must not admit
    a second blocking reader into instant EOF."""
    ha, hb, fifo = fifo_world
    os.mkfifo(fifo)
    ctx = Ctx()
    rfd = _open(ha, ctx, O_RDONLY | O_NONBLOCK)
    assert rfd >= VFD_BASE
    with pytest.raises(Blocked):
        _open(hb, ctx, O_RDONLY)
    # a writer arriving unblocks the parked reader's retry
    wfd = _open(ha, ctx, O_WRONLY)
    assert wfd >= VFD_BASE
    assert _open(hb, ctx, O_RDONLY) >= VFD_BASE


# ---------------------------------------------------------------------
# round watchdog
# ---------------------------------------------------------------------
def test_round_watchdog_fires_and_dumps_state():
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.core.manager import RoundWatchdog

    c = Controller(load_config_str("""
general: {stop_time: 1s}
network:
  faults:
    - {kind: host_crash, time: 500ms, host: b}
hosts:
  a:
    processes: [{path: model:phold, args: msgload=1}]
  b:
    processes: [{path: model:phold, args: msgload=1}]
"""))
    m = c.manager
    fired = []
    wd = RoundWatchdog(m, interval_s=0.3,
                       on_stall=lambda dump: fired.append(dump))
    wd.start()
    try:
        deadline = time.monotonic() + 5
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert wd.fired and fired
    # the dump names every host with its counters
    assert "host a" in fired[0] and "host b" in fired[0]
    assert "events=" in fired[0] and "crashed=" in fired[0]


def test_round_watchdog_quiet_while_progressing():
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.core.manager import RoundWatchdog

    c = Controller(load_config_str("""
general: {stop_time: 1s}
hosts:
  a:
    processes: [{path: model:phold, args: msgload=1}]
  b:
    processes: [{path: model:phold, args: msgload=1}]
"""))
    m = c.manager
    fired = []
    wd = RoundWatchdog(m, interval_s=0.5,
                       on_stall=lambda dump: fired.append(dump))

    stop = threading.Event()

    def tick():
        # synthetic progress: the watchdog samples these counters
        while not stop.is_set():
            m.hosts[0].events_executed += 1
            time.sleep(0.05)

    t = threading.Thread(target=tick, daemon=True)
    wd.start()
    t.start()
    time.sleep(1.2)
    stop.set()
    wd.stop()
    t.join(timeout=2)
    assert not wd.fired and not fired


def test_round_watchdog_config_knob():
    from shadow_tpu.config import load_config_str

    cfg = load_config_str("""
general: {stop_time: 1s}
experimental: {round_watchdog: 30}
hosts:
  a:
    processes: [{path: model:phold}]
""")
    assert cfg.experimental.round_watchdog == 30
    with pytest.raises(ValueError):
        load_config_str("""
general: {stop_time: 1s}
experimental: {round_watchdog: -1}
hosts:
  a:
    processes: [{path: model:phold}]
""")
