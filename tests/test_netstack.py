"""Network-stack tests: token buckets, router queues, UDP, TCP.

Modeled on the reference's tcp test matrix (src/test/tcp/: blocking x
{loopback, lossless, lossy}) at the behavioral level: transfers must
complete, pace at the configured bandwidth, and survive loss via
retransmission.
"""

import pytest

from shadow_tpu import simtime
from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.routing.packet import Packet, Protocol
from shadow_tpu.routing.queues import CoDelQueue, SingleQueue, StaticQueue

MS = simtime.SIMTIME_ONE_MILLISECOND


# ---------------------------------------------------------------- queues
def _pkt(i, size=1400):
    return Packet(src_host=0, packet_id=i, dst_host=1,
                  protocol=Protocol.UDP, size=size)


def test_single_queue_drops_when_full():
    q = SingleQueue()
    assert q.enqueue(_pkt(0), 0)
    assert not q.enqueue(_pkt(1), 0)
    assert q.dequeue(0).packet_id == 0
    assert q.dequeue(0) is None


def test_static_queue_drop_tail():
    q = StaticQueue(capacity=2)
    assert q.enqueue(_pkt(0), 0)
    assert q.enqueue(_pkt(1), 0)
    assert not q.enqueue(_pkt(2), 0)
    assert q.dequeue(0).packet_id == 0
    assert q.dequeue(0).packet_id == 1


def test_codel_passes_low_delay_traffic():
    q = CoDelQueue()
    for i in range(100):
        now = i * MS
        q.enqueue(_pkt(i), now)
        p = q.dequeue(now + 2 * MS)     # 2ms sojourn < 10ms target
        assert p is not None and p.packet_id == i
    assert q.total_dropped == 0


def test_codel_drops_under_standing_queue():
    q = CoDelQueue()
    # build a standing queue: 500 packets arrive at t=0, drain slowly
    for i in range(500):
        q.enqueue(_pkt(i), 0)
    got, now = 0, 0
    for _ in range(500):
        now += 2 * MS                   # sojourn grows far past target
        if q.dequeue(now) is not None:
            got += 1
    assert q.total_dropped > 0
    assert got + q.total_dropped <= 500


# ---------------------------------------------------------------- e2e
TCP_YAML = """
general:
  stop_time: 60s
  seed: 1
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "{bw}" bandwidth_up "{bw}" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ] ]
experimental:
  scheduler_policy: serial
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_tcp_server
      args: size={size}
      start_time: 1s
  client:
    network_node_id: 0
    processes:
    - path: model:tgen_tcp_client
      args: server=server size={size} count={count}
      start_time: 2s
"""


def _run_tcp(bw="100 Mbit", loss=0.0, size="100KiB", count=1):
    cfg = load_config_str(TCP_YAML.format(bw=bw, loss=loss, size=size,
                                          count=count))
    c = Controller(cfg)
    stats = c.run()
    client = next(h for h in c.sim.hosts if h.name == "client")
    server = next(h for h in c.sim.hosts if h.name == "server")
    return stats, client, server


def test_tcp_transfer_lossless():
    stats, client, server = _run_tcp()
    assert client.app.downloads_done == 1
    assert client.app.bytes_received == 100 * 1024
    assert server.app.requests_served == 1


def test_tcp_transfer_lossy_retransmits():
    stats, client, server = _run_tcp(loss=0.05, size="200KiB")
    assert client.app.downloads_done == 1
    assert client.app.bytes_received >= 200 * 1024


def test_tcp_sack_suppresses_spurious_retransmits():
    """On a 5% lossy link the receiver SACKs its out-of-order blocks
    and the sender must never resend a span the peer already holds
    (tcp_retransmit_tally.cc role). With ~140 data segments a blind
    go-back-N would resend far more than the ~dozen actually lost."""
    stats, client, server = _run_tcp(loss=0.05, size="200KiB")
    retrans = server.net.tcp_segments_retransmitted
    sent = server.net.tcp_segments_sent
    lost_est = int(0.05 * sent * 3)  # generous bound on real losses
    assert 0 < retrans <= max(lost_est, 30), (retrans, sent)


AUTOTUNE_YAML = """
general:
  stop_time: {stop}
  seed: 1
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "100 ms" packet_loss 0.0 ] ]
experimental:
  scheduler_policy: serial
  socket_recv_autotune: {tune}
  socket_send_autotune: {tune}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: model:tgen_tcp_server, args: size=8MiB, start_time: 1s}}
  client:
    network_node_id: 0
    processes:
    - {{path: model:tgen_tcp_client, args: server=server size=8MiB,
        start_time: 2s}}
"""


def test_buffer_autotune_lifts_window_limit():
    """200 ms RTT, 1 Gbit: the fixed 174760-byte window caps the flow
    at ~0.9 MB/s (8 MiB needs ~9.6 s), while autotuned buffers grow
    toward the BDP and finish well inside the same 6 s budget
    (reference tcp.c dynamic buffer sizing)."""
    out = {}
    for tune in ("true", "false"):
        c = Controller(load_config_str(
            AUTOTUNE_YAML.format(stop="6s", tune=tune)))
        c.run()
        client = next(h for h in c.sim.hosts if h.name == "client")
        out[tune] = client.app.downloads_done
    assert out["true"] == 1          # autotuned: finished
    assert out["false"] == 0         # window-limited: still going


def test_congestion_algorithm_is_pluggable():
    """tcp_cong.h vtable analogue: reno resolves from the registry; an
    unknown algorithm fails loudly at connect time."""
    from shadow_tpu.host.tcp import (
        CONGESTION_ALGORITHMS,
        RenoCongestion,
        make_congestion,
    )
    assert isinstance(make_congestion("reno"), RenoCongestion)
    with pytest.raises(ValueError, match="unknown tcp congestion"):
        make_congestion("cubic")
    # registry is the extension point
    class _FixedCC(RenoCongestion):
        name = "fixed"
    CONGESTION_ALGORITHMS["fixed"] = _FixedCC
    try:
        assert isinstance(make_congestion("fixed"), _FixedCC)
    finally:
        del CONGESTION_ALGORITHMS["fixed"]


def test_retransmit_tally_ranges():
    from shadow_tpu.host.tcp import RetransmitTally
    t = RetransmitTally()
    t.mark_sacked(100, 200)
    t.mark_sacked(300, 400)
    assert t.is_sacked(100, 200) and t.is_sacked(150, 180)
    assert not t.is_sacked(90, 110) and not t.is_sacked(200, 300)
    t.mark_sacked(150, 350)          # bridges the gap
    assert t.sacked == [[100, 400]]
    t.clear_below(250)
    assert t.sacked == [[250, 400]]
    t.mark_sacked(400, 500)          # adjacent fuses
    assert t.sacked == [[250, 500]]


def test_tcp_bandwidth_pacing():
    # 800 KiB over a 10 Mbit link: ideal ~0.66 s; with handshake,
    # slow start and 20ms RTT it must take >= the line-rate bound and
    # finish well under stop_time
    _, client, _ = _run_tcp(bw="10 Mbit", size="800KiB", count=1)
    assert client.app.downloads_done == 1
    dur_s = client.app._last_download_ns / 1e9
    line_rate_s = (800 * 1024 * 8) / 10e6
    assert dur_s >= 0.9 * line_rate_s, dur_s
    assert dur_s <= 3 * line_rate_s, dur_s


def test_tcp_multiple_downloads():
    _, client, server = _run_tcp(size="50KiB", count=3)
    assert client.app.downloads_done == 3
    assert server.app.requests_served == 3
    assert client.app.bytes_received == 3 * 50 * 1024


def test_tcp_deterministic():
    s1, c1, _ = _run_tcp(loss=0.03, size="100KiB")
    s2, c2, _ = _run_tcp(loss=0.03, size="100KiB")
    assert c1.trace_checksum == c2.trace_checksum
    assert s1.events_executed == s2.events_executed


UDP_YAML = """
general:
  stop_time: 5s
  seed: 1
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler_policy: serial}
hosts:
  a:
    processes:
    - {path: "model:udp_echo_client", args: "peer=b n=5", start_time: 1s}
  b:
    processes:
    - {path: "model:udp_echo_server", start_time: 500ms}
"""


def test_udp_echo():
    from shadow_tpu.models import register_model
    from shadow_tpu.models.base import ModelApp

    class EchoServer(ModelApp):
        def boot(self, ctx):
            ctx.udp_socket(port=9000, on_datagram=self._on)

        def _on(self, ctx, sock, pkt, now):
            sock.sendto(now, pkt.src_host, pkt.tcp.src_port if pkt.tcp
                        else pkt.src_port, pkt.size)

    class EchoClient(ModelApp):
        def __init__(self, args, host_id, n_hosts):
            super().__init__(args, host_id, n_hosts)
            self.n = int(args.get("n", 1))
            self.echoed = 0

        def boot(self, ctx):
            self.sock = ctx.udp_socket(on_datagram=self._on)
            for _ in range(self.n):
                self.sock.sendto(ctx.now, ctx.resolve(
                    self.args.get("peer", "b")), 9000, 100)

        def _on(self, ctx, sock, pkt, now):
            self.echoed += 1

    register_model("udp_echo_server", EchoServer)
    register_model("udp_echo_client", EchoClient)
    cfg = load_config_str(UDP_YAML)
    c = Controller(cfg)
    c.run()
    client = next(h for h in c.sim.hosts if h.name == "a")
    assert client.app.echoed == 5
