"""Model-NIC (bandwidth + CoDel) on both engines.

experimental.model_bandwidth routes raw ctx.send() traffic through the
fluid TX/RX buckets and the event-driven CoDel (host/model_nic.py on
the CPU engines; the same arithmetic vectorized in device/engine.py).
The oracle test is the framework's standard one: bit-identical trace
checksums between the serial CPU run and the device run on a
bandwidth-CONSTRAINED config — bandwidth delays and CoDel drops are
part of the schedule, so any divergence in their arithmetic shows up.
"""

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.host.model_nic import ModelNic, serialize_ns

YAML = """
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "{bw}" bandwidth_up "{bw}" ]
        node [ id 1 bandwidth_down "{bw}" bandwidth_up "{bw}" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]
        edge [ source 0 target 1 latency "10 ms" packet_loss {loss} ]
        edge [ source 1 target 1 latency "10 ms" packet_loss {loss} ]
      ]
experimental:
  scheduler_policy: {policy}
  model_bandwidth: true
  event_capacity: 96
  outbox_capacity: 48
hosts:
  left:
    quantity: 8
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload={msgload} size={size}
      start_time: 10ms
  right:
    quantity: 8
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload={msgload} size={size}
      start_time: 10ms
"""


def _run(policy, bw="1 Mbit", seed=3, loss=0.0, msgload=3,
         size=4096, stop="3s", extra=""):
    yaml = YAML.format(policy=policy, bw=bw, seed=seed, loss=loss,
                       msgload=msgload, size=size, stop=stop)
    if extra:
        yaml = yaml.replace("experimental:", "experimental:\n" + extra)
    c = Controller(load_config_str(yaml))
    stats = c.run()
    return stats, c.sim.hosts


def test_model_nic_unit_tx_rx():
    nic = ModelNic(bw_up_bits=8_000_000, bw_down_bits=8_000_000)
    # 1000 bytes at 8 Mbit = 1 ms serialization
    assert serialize_ns(1000, 8_000_000) == 1_000_000
    assert nic.tx_depart(10_000, 1000) == 10_000
    # second send in the same instant queues behind the first
    assert nic.tx_depart(10_000, 1000) == 1_010_000
    # rx: no standing queue -> no drop, serialization delay applies
    d = nic.rx_deliver(5_000_000, 1000)
    assert d == 6_000_000
    d2 = nic.rx_deliver(5_000_000, 1000)   # queued behind the first
    assert d2 == 7_000_000


def test_model_nic_codel_drops_standing_queue():
    """A long steady overload must trigger CoDel drops (sojourn above
    the 10 ms target for over the 100 ms interval)."""
    nic = ModelNic(bw_up_bits=10**9, bw_down_bits=800_000)
    # 1000-byte packets arriving every 1 ms but taking 10 ms to drain
    drops = 0
    t = 0
    for _ in range(400):
        t += 1_000_000
        if nic.rx_deliver(t, 1000) < 0:
            drops += 1
    assert drops > 0
    assert nic.cd_cnt > 1          # control law escalated


@pytest.mark.parametrize("bw,loss", [("1 Mbit", 0.0),
                                     ("2 Mbit", 0.05)],
                         ids=["constrained", "constrained_lossy"])
def test_device_matches_serial_oracle_with_bandwidth(bw, loss):
    s_stats, s_hosts = _run("serial", bw=bw, loss=loss)
    d_stats, d_hosts = _run("tpu", bw=bw, loss=loss)
    assert d_stats.ok
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.packets_sent == d_stats.packets_sent
    assert s_stats.packets_dropped == d_stats.packets_dropped
    assert s_stats.packets_delivered == d_stats.packets_delivered
    for sh, dh in zip(s_hosts, d_hosts):
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_device_tpu_default_strategies_with_bandwidth():
    """model_bandwidth under the strategies production TPU actually
    auto-selects (merge_strategy: global, pop_strategy: onehot) vs
    the serial oracle — the other MB oracle tests run on CPU where
    both auto-resolve to the CPU-tuned paths, so without this pin the
    on-chip MB combination would ship untested (READY-reinsert rows
    through the global double-sort merge, fluid-NIC pops through the
    one-hot head reads)."""
    extra = "  merge_strategy: global\n  pop_strategy: onehot"
    s_stats, s_hosts = _run("serial", bw="2 Mbit", loss=0.05)
    d_stats, d_hosts = _run("tpu", bw="2 Mbit", loss=0.05,
                            extra=extra)
    assert d_stats.ok
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.packets_sent == d_stats.packets_sent
    assert s_stats.packets_dropped == d_stats.packets_dropped
    assert s_stats.packets_delivered == d_stats.packets_delivered
    for sh, dh in zip(s_hosts, d_hosts):
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_bandwidth_actually_constrains():
    """Same workload, 1000x less bandwidth -> fewer deliveries by the
    stop time (serialization pushes traffic past the horizon) and/or
    CoDel drops; and the constrained run must differ from the
    unconstrained schedule."""
    wide, _ = _run("serial", bw="1 Gbit")
    narrow, _ = _run("serial", bw="500 Kbit", size=16384)
    assert narrow.packets_delivered < wide.packets_delivered


def test_hybrid_matches_serial_with_bandwidth():
    s_stats, s_hosts = _run("serial", bw="1 Mbit")
    h_stats, h_hosts = _run("hybrid", bw="1 Mbit")
    assert s_stats.packets_sent == h_stats.packets_sent
    assert s_stats.packets_dropped == h_stats.packets_dropped
    for sh, hh in zip(s_hosts, h_hosts):
        assert sh.trace_checksum == hh.trace_checksum, sh.name
