"""Bit-identity of the numpy threefry replica against jax."""

import numpy as np

import jax
import jax.numpy as jnp

from shadow_tpu.utils import nprng
from shadow_tpu.utils.rng import (
    PURPOSE_PACKET_DROP,
    base_key,
    uniform01,
)


def test_threefry_core_matches_jax():
    from jax._src import prng as jprng
    rng = np.random.default_rng(0)
    k1 = rng.integers(0, 2**32, 16, dtype=np.uint32)
    k2 = rng.integers(0, 2**32, 16, dtype=np.uint32)
    x0 = rng.integers(0, 2**32, 16, dtype=np.uint32)
    x1 = rng.integers(0, 2**32, 16, dtype=np.uint32)
    for i in range(16):
        ours = nprng.threefry2x32(k1[i], k2[i], x0[i], x1[i])
        count = jnp.array([x0[i], x1[i]], dtype=jnp.uint32)
        theirs = jprng.threefry_2x32(
            (jnp.uint32(k1[i]), jnp.uint32(k2[i])), count)
        assert int(ours[0]) == int(theirs[0])
        assert int(ours[1]) == int(theirs[1])


def test_seed_key_matches_prngkey():
    for seed in [0, 1, 42, 2**31 - 1, 2**32 + 17, 2**62 + 5]:
        jk = jax.random.PRNGKey(seed)
        ours = nprng.seed_key(seed)
        assert int(jk[0]) == int(ours[0]), seed
        assert int(jk[1]) == int(ours[1]), seed


def test_fold_in_matches_jax():
    for seed in [1, 7, 123456]:
        jk = jax.random.PRNGKey(seed)
        ok = nprng.seed_key(seed)
        for data in [0, 1, 3, 1000, 2**31]:
            jf = jax.random.fold_in(jk, data)
            of = nprng.fold_in(ok, data)
            assert int(jf[0]) == int(of[0])
            assert int(jf[1]) == int(of[1])


def test_uniform_matches_jax():
    for seed in [1, 7]:
        jk = jax.random.PRNGKey(seed)
        ok = nprng.seed_key(seed)
        ju = float(jax.random.uniform(jk, (), dtype=jnp.float32))
        ou = float(nprng.uniform01(ok))
        assert ju == ou


def test_packet_chain_matches_device_chain():
    seed = 42
    jkey = base_key(seed)
    for host, seq in [(0, 0), (3, 100), (17, 2**20)]:
        jv = float(uniform01(jkey, PURPOSE_PACKET_DROP, host, seq))
        ov = float(nprng.packet_uniform(seed, PURPOSE_PACKET_DROP,
                                        host, seq))
        assert jv == ov, (host, seq)


def test_vectorized_packet_uniform():
    seqs = np.arange(1000)
    vals = nprng.packet_uniform(7, PURPOSE_PACKET_DROP, 3, seqs)
    assert vals.shape == (1000,)
    assert ((vals >= 0) & (vals < 1)).all()
    # spot-check a few against the scalar path
    for i in [0, 500, 999]:
        assert vals[i] == nprng.packet_uniform(7, PURPOSE_PACKET_DROP, 3, i)
