"""Stale-heartbeat detection (device/supervise.HeartbeatMonitor) and
the campaign server watchdog that consumes it — all on frozen/fake
clocks, so every staleness verdict in here is deterministic.

The monitor learns the run's own heartbeat cadence (EWMA of healthy
gaps) instead of trusting a configured wall-time number: device
heartbeats fire per SIM interval, so their wall cadence depends on
throughput, and a fixed wall threshold would cry wolf on slow
configs and sleep through fast ones.
"""

import json
import os

from shadow_tpu.device.supervise import HeartbeatMonitor
from shadow_tpu.serve import Journal
from shadow_tpu.serve.server import CampaignServer


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

def test_monitor_learns_cadence_and_flags_wide_gap():
    clk = FakeClock()
    mon = HeartbeatMonitor(3, clock=clk)
    for t in (0.0, 1.0, 2.0, 3.1, 4.0):     # healthy ~1s cadence
        clk.t = t
        mon.beat()
    assert mon.stale_events == 0
    clk.t = 12.0                             # 8s gap >> 3x EWMA
    mon.beat()
    assert mon.stale_events == 1
    # the stale gap must NOT be folded into the learned cadence —
    # otherwise one stall doubles the threshold and hides the next
    clk.t = 20.0
    mon.beat()
    assert mon.stale_events == 2


def test_monitor_live_staleness_probe_without_a_beat():
    # the watchdog polls stale() BETWEEN beats — a wedged run never
    # beats again, so detection cannot wait for the next beat()
    clk = FakeClock()
    mon = HeartbeatMonitor(3, clock=clk)
    clk.t = 0.0
    mon.beat()
    clk.t = 1.0
    mon.beat()                               # learned cadence ~1s
    clk.t = 3.5
    assert not mon.stale()                   # 2.5s < 3 x 1s
    clk.t = 9.0
    assert mon.stale()                       # 8s > 3 x 1s
    assert mon.gap() == 8.0


def test_monitor_is_quiet_before_a_cadence_exists():
    clk = FakeClock()
    mon = HeartbeatMonitor(3, clock=clk)
    assert not mon.stale()                   # no beats at all
    mon.beat()
    clk.t = 1000.0
    assert not mon.stale()                   # one beat = no cadence yet


def test_monitor_clamps_k_to_at_least_two():
    # k=1 would flag ordinary jitter (any gap over the average);
    # the schema allows >= 0 but the monitor refuses to be that jumpy
    assert HeartbeatMonitor(0).k == 2
    assert HeartbeatMonitor(1).k == 2
    assert HeartbeatMonitor(5).k == 5


# ---------------------------------------------------------------------------
# the server watchdog consuming the monitor
# ---------------------------------------------------------------------------

class _StubGuard:
    def __init__(self):
        self.requested = False

    def request(self):
        self.requested = True


class _StubRunner:
    def __init__(self, mon, guard):
        self.hb_monitor = mon
        self.guard = guard


class _StubController:
    def __init__(self, runner):
        self.runner = runner


def _wedged_holder(srv, clk):
    """A slot whose campaign beat twice (cadence ~1s) then wedged."""
    from shadow_tpu.serve.journal import Campaign
    import threading

    camp = Campaign(cid="c0000", config="x.yaml", state="RUNNING",
                    attempts=1)
    srv.campaigns["c0000"] = camp
    mon = HeartbeatMonitor(3, clock=clk)
    mon.beat()
    clk.t = 1.0
    mon.beat()
    guard = _StubGuard()
    holder = {"camp": camp, "stats": None, "error": None,
              "controller": _StubController(_StubRunner(mon, guard)),
              "done": threading.Event(), "preempt_for": "",
              "stale_since": None, "t_launch": clk.t}
    return holder, guard


def test_watchdog_requests_drain_then_kills_past_grace(tmp_path):
    clk = FakeClock()
    spool = str(tmp_path / "spool")
    srv = CampaignServer(spool, poll_s=0.0, watchdog_grace_s=10.0,
                         clock=clk)
    holder, guard = _wedged_holder(srv, clk)

    clk.t = 2.0
    assert not srv._watchdog(holder)         # healthy: 1s since beat
    assert not guard.requested

    clk.t = 20.0                             # 19s gap >> 3 x 1s
    assert not srv._watchdog(holder)         # first detection: drain
    assert guard.requested                   # requested, slot kept
    assert holder["stale_since"] == 20.0

    clk.t = 25.0
    assert not srv._watchdog(holder)         # inside the grace window

    clk.t = 31.0                             # grace (10s) exhausted
    assert srv._watchdog(holder)             # supervised kill
    camp = srv.campaigns["c0000"]
    assert camp.state == "PREEMPTED"
    assert "supervised kill" in camp.diagnostic
    assert srv.slo["stale_kills"] == 1
    rows = [json.loads(line) for line in
            open(os.path.join(spool, "journal.jsonl"),
                 encoding="utf-8")]
    assert any(r.get("event") == "stale_heartbeat" for r in rows)
    assert rows[-1]["state"] == "PREEMPTED"
    # the campaign is schedulable again
    assert srv._pick() is camp


def test_watchdog_recovers_when_beats_return(tmp_path):
    clk = FakeClock()
    srv = CampaignServer(str(tmp_path / "spool"), poll_s=0.0,
                         watchdog_grace_s=10.0, clock=clk)
    holder, guard = _wedged_holder(srv, clk)
    clk.t = 20.0
    srv._watchdog(holder)                    # drain requested
    assert holder["stale_since"] == 20.0
    mon = holder["controller"].runner.hb_monitor
    clk.t = 21.0
    mon.beat()                               # the run woke back up
    clk.t = 21.5
    assert not srv._watchdog(holder)
    assert holder["stale_since"] is None     # staleness cleared


def test_journal_reexport():
    # the serve package re-exports the journal surface the watchdog
    # tests use — keep the public import path stable
    assert Journal is not None
