"""Occupancy telemetry + adaptive capacity planner (device/capacity.py).

Three contracts:
* the engine's occ_* high-water marks equal brute-force occupancies
  replayed from the serial oracle's event trace (same window loop,
  pure Python);
* a planner-sized engine produces bit-identical per-host trace
  checksums to the statically-sized engine (capacities are purely a
  performance lever while nothing overflows);
* a plan that undershoots (warm-up slice ends before real traffic)
  trips the loud overflow counters, re-plans with doubled headroom,
  and COMPLETES with the static run's trace instead of failing.
"""

import json
import os

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.config.loader import load_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.event import KIND_BOOT, KIND_PACKET
from shadow_tpu.device import capacity

PHOLD_YAML = """
general:
  stop_time: {stop}
  seed: 9
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "30 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "30 ms" packet_loss 0.0 ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 64
  outbox_capacity: 16
{extra}hosts:
  left:
    quantity: {q}
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload={msgload}
      start_time: 100ms
  right:
    quantity: {q}
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload={msgload}
      start_time: 150ms
"""


def _cfg(policy, stop="1s", q=3, msgload=2, extra=""):
    return load_config_str(PHOLD_YAML.format(
        policy=policy, stop=stop, q=q, msgload=msgload, extra=extra))


def _checksums(hosts):
    return [h.trace_checksum for h in hosts]


# ---------------------------------------------------------------------
# (a) telemetry vs brute force from the serial oracle's trace
# ---------------------------------------------------------------------

def _replay_windows(boots, packets, H, L, stop, msgload, H_loc, S,
                    split_in=False):
    """Replay the device engine's window loop in pure Python from the
    oracle's event times: windows open at the global min pending time,
    close at min(nxt + lookahead, stop); events with time < win_end
    pop (emitting their sends), packets sent in the window arrive at
    its flush. Returns per-host/per-pair occupancy high-water marks —
    what the engine's reduction-only occ_* telemetry must equal."""
    live = [(t, h, msgload) for t, h in boots]   # (exec_t, host, sends)
    pkts = sorted(packets)                        # by send_time
    ip = 0
    occ_heap, occ_in, occ_ob = [0] * H, [0] * H, [0] * H
    occ_x = np.zeros((S, S), dtype=int)
    trips_max, phases = 0, 0
    while live:
        nxt = min(t for t, _, _ in live)
        if nxt >= stop:
            break
        win_end = min(nxt + L, stop)
        popped = [e for e in live if e[0] < win_end]
        live = [e for e in live if e[0] >= win_end]
        ob, per_exec = [0] * H, [0] * H
        # the windowed all_to_all path accepts self-shard and remote
        # arrivals as two separate IN-wide blocks, so its occ_in is
        # the per-block max; single-shard (and the global merge)
        # windows them jointly
        inn, inn_far = [0] * H, [0] * H
        for _, h, k in popped:
            ob[h] += k
            per_exec[h] += 1
        x = np.zeros((S, S), dtype=int)
        while ip < len(pkts) and pkts[ip][0] < win_end:
            send_t, exec_t, src, dst = pkts[ip]
            ip += 1
            assert send_t >= nxt, "arrival from a pre-window send"
            live.append((exec_t, dst, 1))
            if split_in and src // H_loc != dst // H_loc:
                inn_far[dst] += 1
            else:
                inn[dst] += 1
            if src // H_loc != dst // H_loc:
                x[src // H_loc][dst // H_loc] += 1
        heap_now = [0] * H
        for _, h, _ in live:
            heap_now[h] += 1
        for h in range(H):
            occ_ob[h] = max(occ_ob[h], ob[h])
            occ_in[h] = max(occ_in[h], inn[h], inn_far[h])
            occ_heap[h] = max(occ_heap[h], heap_now[h])
        occ_x = np.maximum(occ_x, x)
        trips_max = max(trips_max, max(per_exec))
        phases += 1
    # the oracle trace is taken from a LONGER run so sends still in
    # flight at `stop` are visible (they ride the exchange and sit in
    # heaps without ever executing); anything left over must be sends
    # from events at/after `stop` — outside the replayed run entirely
    assert all(p[0] >= stop for p in pkts[ip:]), \
        "trace packets the replay never delivered"
    return dict(heap=occ_heap, inn=occ_in, ob=occ_ob, x=occ_x,
                trips=trips_max, phases=phases)


@pytest.mark.parametrize("merge", [
    "auto",
    # the global-merge path measures occ_in/occ_heap with different
    # arithmetic (searchsorted segments); covered outside tier-1
    pytest.param("global", marks=pytest.mark.slow),
])
def test_occupancy_marks_match_trace_brute_force(merge):
    msgload, q = 2, 3
    trace = []
    # the oracle runs PAST the device stop: events before `stop` are
    # identical (DES prefix determinism), and the longer trace also
    # shows packets sent before `stop` that deliver after it — the
    # device ships and heap-inserts those without executing them, so
    # the replay must see them to match occ_in/occ_x/occ_heap
    s = Controller(_cfg("serial", stop="1200ms", q=q,
                        msgload=msgload), trace=trace)
    s.run()

    d = Controller(_cfg("tpu", q=q, msgload=msgload,
                        extra=f"  merge_strategy: {merge}\n"))
    stats = d.run()
    assert stats.ok
    eng = d.runner.engine
    H = len(d.sim.hosts)
    L = max(1, d.sim.lookahead)
    stop = d.cfg.general.stop_time

    vertex = np.asarray(d.sim.netmodel.host_vertex)
    lat = np.asarray(d.sim.topology.latency_ns)
    boots = [(t, h) for h, t, *_ in d.sim.starts]
    packets = []
    for t, dst, src, kind in trace:
        if kind == KIND_PACKET:
            send_t = t - int(lat[vertex[src], vertex[dst]])
            packets.append((send_t, t, src, dst))
        else:
            assert kind == KIND_BOOT, f"unexpected kind {kind}"

    ref = _replay_windows(boots, packets, H, L, stop, msgload,
                          eng.H_loc, eng.n_shards,
                          split_in=(eng.n_shards > 1
                                    and merge != "global"))

    final = d.runner.final_state
    np.testing.assert_array_equal(
        np.asarray(final["occ_heap"])[:H], ref["heap"])
    np.testing.assert_array_equal(
        np.asarray(final["occ_in"])[:H], ref["inn"])
    np.testing.assert_array_equal(
        np.asarray(final["occ_ob"])[:H], ref["ob"])
    if merge != "global":
        # the global merge sorts all rows jointly — there is no
        # per-shard-pair exchange, so occ_x legitimately stays 0
        np.testing.assert_array_equal(np.asarray(final["occ_x"]),
                                      ref["x"])
    assert int(np.asarray(final["occ_phases"]).max()) == ref["phases"]
    # the pop loop runs one iteration per runnable event per host
    # (burst_pops=1 here); dirty-slot stalls could only add iterations
    trips = int(np.asarray(final["occ_trips"]).max())
    assert trips >= ref["trips"]
    assert stats.occupancy is not None
    assert stats.occupancy["measured"]["heap_rows_max"] == \
        max(ref["heap"])


# ---------------------------------------------------------------------
# planner pure functions
# ---------------------------------------------------------------------

def test_plan_sizes_from_measurements():
    record = {"measured": {
        "heap_rows_max": 20, "outbox_rows_max": 6,
        "arrivals_per_flush_max": 10, "exchange_rows_max": 4,
        "pop_trips_max": 5, "phases": 100,
        "overflow": 0, "x_overflow": 0}}
    p = capacity.plan(record, per_iter=3, floor_iters=4, n_shards=4)
    assert p["event_capacity"] == 32            # ceil(20*1.5)+2
    assert p["exchange_in_capacity"] == 17      # ceil(10*1.5)+2
    assert p["outbox_capacity"] == 10 * 3       # ceil(5*1.5)+2 iters
    assert p["outbox_compact"] == 11            # ceil(6*1.5)+2 < 3/4*30
    assert p["exchange_capacity"] == 8          # ceil(4*1.5)+2
    # single shard: the exchange axis keeps the engine's auto-sizing
    p1 = capacity.plan(record, per_iter=3, n_shards=1)
    assert p1["exchange_capacity"] == 0
    # a compaction width near the outbox width stops paying for itself
    record["measured"]["outbox_rows_max"] = 25
    p2 = capacity.plan(record, per_iter=3, floor_iters=4, n_shards=1)
    assert p2["outbox_compact"] == 0


def test_plan_prefers_full_run_maxima():
    """A saved record carries warm-up (`measured`) and full-run
    (`final_measured`) maxima; plan() sizes from the elementwise max
    so a capacity_plan: <path> replay covers steady state."""
    record = {
        "measured": {
            "heap_rows_max": 20, "outbox_rows_max": 6,
            "arrivals_per_flush_max": 10, "exchange_rows_max": 4,
            "pop_trips_max": 5, "phases": 100,
            "overflow": 0, "x_overflow": 0},
        "final_measured": {
            "heap_rows_max": 90, "outbox_rows_max": 3,
            "arrivals_per_flush_max": 10, "exchange_rows_max": 4,
            "pop_trips_max": 5, "phases": 400,
            "overflow": 0, "x_overflow": 0},
    }
    p = capacity.plan(record, per_iter=3, floor_iters=4, n_shards=1)
    assert p["event_capacity"] == 137           # ceil(90*1.5)+2
    assert p["outbox_compact"] == 11            # max(6,3) -> 6


def test_widen_doubles_offending_dimension():
    eff = {"E": 16, "IN": 8, "CAP": 32, "CX": 8, "OB": 24,
           "B": 4, "M_out": 6, "n_shards": 2}
    out = capacity.widen({}, ("event_capacity",
                              "exchange_in_capacity"), eff)
    assert out == {"event_capacity": 32, "exchange_in_capacity": 16}
    out = capacity.widen(out, ("event_capacity",), eff)
    assert out["event_capacity"] == 64          # doubles the override
    out = capacity.widen({}, ("exchange_capacity",
                              "outbox_compact"), eff)
    assert out["exchange_capacity"] == 64
    assert out["outbox_compact"] == 16          # 2*CX, still < OB
    # a compaction width that cannot double under OB turns off
    out = capacity.widen({}, ("outbox_compact",),
                         dict(eff, CX=16, OB=24))
    assert out["outbox_compact"] == 0


def test_record_roundtrip_and_validation(tmp_path):
    rec = {"format": capacity.FORMAT, "measured": {"heap_rows_max": 3},
           "workload": {"app": "X", "n_hosts": 4}}
    path = str(tmp_path / "OCC_X_4.json")
    capacity.save_record(rec, path)
    assert capacity.load_record(path) == rec
    with open(path, "w") as f:
        json.dump({"format": 999}, f)
    with pytest.raises(ValueError, match="format"):
        capacity.load_record(path)


def test_grow_heaps_pads_and_refuses_shrink():
    INF = np.int64(1) << np.int64(62)
    st = {k: np.arange(6, dtype=np.int64).reshape(2, 3)
          for k in ("ht", "hk", "hm", "hv", "hw")}
    out = capacity.grow_heaps(st, 5)
    assert out["ht"].shape == (2, 5)
    assert (out["ht"][:, 3:] == INF).all()
    assert (out["hm"][:, 3:] == 0).all()
    np.testing.assert_array_equal(out["hk"][:, :3], st["hk"])
    assert capacity.grow_heaps(st, 3) is not st  # no-op copy
    with pytest.raises(ValueError, match="shrink"):
        capacity.grow_heaps(st, 2)


# ---------------------------------------------------------------------
# (b) planner-sized runs are bit-identical to static runs
# ---------------------------------------------------------------------

def test_planned_phold_trace_bit_identical(tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    s = Controller(_cfg("tpu"))
    s_stats = s.run()
    assert s_stats.ok

    # warm-up must reach steady state for a first-try plan (the
    # default stop/8 = 125ms sees little more than the 100ms boots)
    p = Controller(_cfg(
        "tpu",
        extra="  capacity_plan: auto\n  capacity_warmup: 600ms\n"))
    p_stats = p.run()
    assert p_stats.ok
    assert p_stats.replans == 0          # warm-up covered steady state
    assert _checksums(p.sim.hosts) == _checksums(s.sim.hosts)
    assert p_stats.events_executed == s_stats.events_executed
    assert p_stats.packets_sent == s_stats.packets_sent

    # the plan actually tightened something vs the static knobs
    planned = p_stats.occupancy["planned"]
    static = p_stats.occupancy["static"]
    assert planned != static
    assert planned["event_capacity"] < 64

    # the OCC record landed and replays through capacity_plan: <path>
    files = [f for f in os.listdir(tmp_path) if f.startswith("OCC_")]
    assert len(files) == 1
    path = os.path.join(str(tmp_path), files[0])
    r = Controller(_cfg("tpu", extra=f"  capacity_plan: {path}\n"))
    r_stats = r.run()
    assert r_stats.ok
    assert _checksums(r.sim.hosts) == _checksums(s.sim.hosts)


@pytest.mark.slow
@pytest.mark.parametrize("example,stop,warmup", [
    ("examples/tgen_100.yaml", "4s", "3s"),
    ("examples/phold.yaml", "1s", "500ms"),
])
def test_planned_example_trace_bit_identical(example, stop, warmup,
                                             tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, example)
    s = Controller(load_config(path, overrides=[
        f"general.stop_time={stop}"]))
    s_stats = s.run()
    assert s_stats.ok

    p = Controller(load_config(path, overrides=[
        f"general.stop_time={stop}",
        "experimental.capacity_plan=auto",
        f"experimental.capacity_warmup={warmup}"]))
    p_stats = p.run()
    assert p_stats.ok
    assert _checksums(p.sim.hosts) == _checksums(s.sim.hosts)
    assert p_stats.events_executed == s_stats.events_executed
    rec = p_stats.occupancy
    assert rec["measured"]["overflow"] == 0
    assert rec["planned"].keys() == rec["static"].keys()


# ---------------------------------------------------------------------
# (c) a bad plan overflows loudly, re-plans, and completes
# ---------------------------------------------------------------------

def test_forced_overflow_replans_and_completes(tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    # default q/msgload on purpose: the static engine here has the
    # same shapes as the other tests', so its compile is a cache hit
    s = Controller(_cfg("tpu"))
    s_stats = s.run()
    assert s_stats.ok

    # warm-up ends at 50ms — before the first boot at 100ms — so the
    # plan is sized on an EMPTY slice (floors only) and the real run
    # must overflow; the retry loop re-plans and still bit-matches
    f = Controller(_cfg(
        "tpu",
        extra="  capacity_plan: auto\n  capacity_warmup: 50ms\n"))
    f_stats = f.run()
    assert f_stats.ok, "re-plan/retry loop failed to absorb overflow"
    assert f_stats.replans >= 1
    assert _checksums(f.sim.hosts) == _checksums(s.sim.hosts)
    assert f_stats.events_executed == s_stats.events_executed
    assert f_stats.packets_sent == s_stats.packets_sent
    assert f_stats.packets_sent > 0
    rec = f_stats.occupancy
    assert rec["replans"] == f_stats.replans
    # the final (widened) capacities held: counters clean at the end
    assert rec["final_measured"]["overflow"] == 0
    assert rec["final_measured"]["x_overflow"] == 0


def test_static_overflow_refuses_checkpoint(tmp_path):
    """A static run that overflows (events lost) must not leave a
    valid-looking checkpoint behind — a resume from it would silently
    replay the loss (same refusal as the max_rounds budget path)."""
    ck = str(tmp_path / "state.npz")
    cfg = _cfg("tpu", extra=f"  checkpoint_save: {ck}\n")
    cfg.experimental.event_capacity = 2
    stats = Controller(cfg).run()
    assert not stats.ok
    assert not os.path.exists(ck)


def test_warmup_without_auto_rejected():
    with pytest.raises(ValueError, match="capacity_warmup"):
        _cfg("tpu", extra="  capacity_warmup: 50ms\n")


def test_capacity_plan_requires_tpu_policy():
    with pytest.raises(ValueError, match="capacity_plan"):
        _cfg("serial", extra="  capacity_plan: auto\n")
