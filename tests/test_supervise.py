"""Supervised runs (device/supervise.py + experimental.state_audit).

The supervision layer's three guarantees, pinned:
* periodic validated checkpoints rotate (last-K, atomic) and a resume
  from the rotation bit-matches the uninterrupted run;
* SIGTERM drains gracefully — the in-flight segment finishes, a
  resume checkpoint lands, stats mark the run preempted — and the
  resumed run is bit-identical;
* transient dispatch errors retry from the last validated state, and
  exhausted retries fail over to the hybrid backend instead of
  aborting.
Plus the health-word audit: clean runs stay bit-identical with it on,
corrupted states are named, and with supervision disabled the
compiled device program is unchanged (no audit leaves, identical
lowering).
"""

import glob
import json
import os
import signal

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import supervise

YAML = """
general:
  stop_time: 800ms
  seed: 9
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


def _run(extra=""):
    c = Controller(load_config_str(YAML.format(extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


# ---------------------------------------------------------------------------
# atomic artifact writes (utils/artifacts.py)
# ---------------------------------------------------------------------------

def test_atomic_write_json_lands_whole_or_not_at_all(tmp_path):
    from shadow_tpu.utils.artifacts import atomic_write_json

    path = str(tmp_path / "sub" / "rec.json")
    atomic_write_json({"a": 1, "b": [2, 3]}, path)
    with open(path) as f:
        assert json.load(f) == {"a": 1, "b": [2, 3]}
    # no tmp debris after a successful write
    assert os.listdir(os.path.dirname(path)) == ["rec.json"]

    # a failing serialization leaves nothing behind (not even a tmp)
    with pytest.raises(TypeError):
        atomic_write_json({"bad": object()}, str(tmp_path / "x.json"))
    assert not glob.glob(str(tmp_path / "x.json*"))


# ---------------------------------------------------------------------------
# checkpoint_load rotation resolution
# ---------------------------------------------------------------------------

def test_resolve_checkpoint_skips_corrupt_newest(tmp_path):
    base = str(tmp_path / "ck.npz")
    good = f"{base}.t{500:015d}"
    bad = f"{base}.t{900:015d}"
    meta = {"format": 1, "sim_time": 500, "final_stop": 0,
            "fingerprint": {}, "keys": []}
    with open(good, "wb") as f:
        np.savez_compressed(f, __meta__=json.dumps(meta))
    # the newest entry is a truncated decoy — exactly what a SIGKILL
    # mid-write used to leave; the resolver must fall back
    with open(bad, "wb") as f:
        f.write(b"PK\x03\x04 not really an npz")
    assert supervise.resolve_checkpoint(base) == good
    # a concrete existing file always wins
    assert supervise.resolve_checkpoint(good) == good
    with pytest.raises(ValueError, match="nothing to resume"):
        supervise.resolve_checkpoint(str(tmp_path / "absent.npz"))


# ---------------------------------------------------------------------------
# rotation + graceful preemption + resume bit-identity (tier-1 fast path;
# the full mid-campaign preemption of examples/ensemble_seed_sweep.yaml is
# the slow gate test below)
# ---------------------------------------------------------------------------

def test_rotation_prune_preempt_and_resume_bitmatch(tmp_path,
                                                    monkeypatch):
    full_stats, full_c = _run()
    assert full_stats.ok
    ref = _sig(full_stats, full_c)

    # supervised run, SIGTERM raised synchronously after the second
    # dispatch segment completes — the guard drains at the next
    # boundary, so the preemption point is deterministic
    base = str(tmp_path / "ck.npz")
    import shadow_tpu.device.engine as eng
    orig = eng.DeviceEngine.run
    calls = {"n": 0}

    def poking(self, state, stop=None, final_stop=None):
        out = orig(self, state, stop=stop, final_stop=final_stop)
        calls["n"] += 1
        if calls["n"] == 3:
            signal.raise_signal(signal.SIGTERM)
        return out

    monkeypatch.setattr(eng.DeviceEngine, "run", poking)
    pre_stats, _ = _run(
        f"  checkpoint_save: {base}\n"
        f"  checkpoint_every: 200ms\n"
        f"  checkpoint_keep: 2\n"
        f"  state_audit: true")
    monkeypatch.setattr(eng.DeviceEngine, "run", orig)
    assert pre_stats.preempted
    assert pre_stats.end_time == 600_000_000  # drained at boundary 3
    assert pre_stats.resume_path
    assert os.path.exists(pre_stats.resume_path)
    # rotation pruned to checkpoint_keep entries, newest retained
    rot = supervise.rotation_entries(base)
    assert len(rot) == 2
    assert rot[-1][1] == pre_stats.resume_path
    # the preempted run stopped early: strictly less work than full
    assert pre_stats.events_executed < full_stats.events_executed
    # the rotation entries carry the validation stamp
    from shadow_tpu.device import checkpoint
    assert checkpoint.peek_meta(rot[-1][1])["audit"] == {
        "enabled": True, "violations": 0}

    # resume from the BASE path (rotation-resolved), audit off — the
    # audit leaves are auxiliary and must not pin the resume
    res_stats, res_c = _run(f"  checkpoint_load: {base}")
    assert res_stats.ok and not res_stats.preempted
    assert _sig(res_stats, res_c) == ref

    # resume with audit ON from the same checkpoint: the reseeded
    # conservation ledger must stay clean to the end
    res2_stats, res2_c = _run(
        f"  checkpoint_load: {base}\n  state_audit: true")
    assert res2_stats.ok
    assert _sig(res2_stats, res2_c) == ref


# ---------------------------------------------------------------------------
# health-word audit
# ---------------------------------------------------------------------------

def test_audit_trace_invariant_and_leaves(tmp_path):
    s_off, c_off = _run()
    s_on, c_on = _run("  state_audit: true")
    assert _sig(s_off, c_off) == _sig(s_on, c_on)
    # audited run: leaves present, word clean
    state = c_on.runner.final_state
    assert int(np.asarray(state["aud"]).max()) == 0
    assert "aud_tx" in state
    # un-audited run: no audit leaves anywhere in the state
    assert not any(k.startswith("aud") for k in c_off.runner.final_state)


def test_audit_detects_corrupted_state():
    import jax
    import jax.numpy as jnp

    _, c = _run("  state_audit: true")
    r = c.runner
    state = r.engine.init_state(r.sim.starts)
    bad = np.array(jax.device_get(state["n_sent"]))
    bad[0] = -7
    state["n_sent"] = jax.device_put(jnp.asarray(bad),
                                     state["n_sent"].sharding)
    state, _ = r.engine.run(state, stop=200_000_000,
                            final_stop=800_000_000)
    aud = np.asarray(jax.device_get(state["aud"]))
    assert aud.any()
    word = int(np.bitwise_or.reduce(aud, axis=None))
    assert "counter-negativity" in supervise.decode_audit(word)
    with pytest.raises(supervise.AuditFailure,
                       match="counter-negativity"):
        supervise.check_audit(state, where="unit test")


def test_supervision_knobs_do_not_change_program(tmp_path):
    """With the audit off, none of the supervision knobs (periodic
    checkpoints, retries, failover) may leak into the compiled device
    program — they are host-side orchestration. Pinned by comparing
    the lowered program text."""
    import jax.numpy as jnp

    _, plain = _run()
    base = str(tmp_path / "ck.npz")
    _, sup = _run(
        f"  checkpoint_save: {base}\n"
        f"  checkpoint_every: 200ms\n"
        f"  dispatch_retries: 3\n"
        f"  failover: hybrid")

    def lowered(c):
        e = c.runner.engine
        state = e.init_state(c.sim.starts)
        import jax
        from jax.sharding import NamedSharding
        repl = NamedSharding(e.mesh, e._repl_spec)
        hv = jax.device_put(jnp.asarray(e.host_vertex), repl)
        return e._run.lower(state, hv, e.world(), jnp.int64(100),
                            jnp.int64(100)).as_text()

    assert lowered(plain) == lowered(sup)


# ---------------------------------------------------------------------------
# dispatch retry + failover
# ---------------------------------------------------------------------------

def test_transient_dispatch_retry_bitmatch(monkeypatch):
    full_stats, full_c = _run()
    ref = _sig(full_stats, full_c)

    import shadow_tpu.device.engine as eng
    orig = eng.DeviceEngine.run
    calls = {"n": 0}

    def flaky(self, state, stop=None, final_stop=None):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        return orig(self, state, stop=stop, final_stop=final_stop)

    monkeypatch.setattr(eng.DeviceEngine, "run", flaky)
    stats, c = _run("  dispatch_retries: 2\n"
                    "  dispatch_retry_backoff: 0.0\n"
                    "  dispatch_segment: 200ms")
    assert stats.ok
    assert stats.retries == 1
    assert _sig(stats, c) == ref

    # the retry budget is per segment (CONSECUTIVE failures): two
    # unrelated incidents in different segments each recover under
    # dispatch_retries: 1 — they must not pool into exhaustion
    calls["n"] = 0

    def flaky_twice(self, state, stop=None, final_stop=None):
        calls["n"] += 1
        if calls["n"] in (2, 5):
            raise RuntimeError("UNAVAILABLE: injected hiccup")
        return orig(self, state, stop=stop, final_stop=final_stop)

    monkeypatch.setattr(eng.DeviceEngine, "run", flaky_twice)
    stats2, c2 = _run("  dispatch_retries: 1\n"
                      "  dispatch_retry_backoff: 0.0\n"
                      "  dispatch_segment: 200ms")
    assert stats2.ok
    assert stats2.retries == 2
    assert _sig(stats2, c2) == ref

    # a non-transient error is NOT retried
    def broken(self, state, stop=None, final_stop=None):
        raise RuntimeError("XlaRuntimeError: INVALID_ARGUMENT: bug")

    monkeypatch.setattr(eng.DeviceEngine, "run", broken)
    with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
        _run("  dispatch_retries: 5\n"
             "  dispatch_retry_backoff: 0.0")


def test_failover_to_hybrid_finishes_the_run(monkeypatch, tmp_path,
                                             caplog):
    import logging

    ref_stats, ref_c = _run()
    ref = _sig(ref_stats, ref_c)

    import shadow_tpu.device.engine as eng

    def dead(self, state, stop=None, final_stop=None):
        raise RuntimeError("UNAVAILABLE: device went away")

    monkeypatch.setattr(eng.DeviceEngine, "run", dead)
    with caplog.at_level(logging.ERROR):
        stats, c = _run(
            f"  failover: hybrid\n"
            f"  checkpoint_save: {tmp_path / 'fo.npz'}\n"
            f"  dispatch_segment: 200ms")
    assert stats.ok
    assert stats.failover_checkpoint
    assert os.path.exists(stats.failover_checkpoint)
    assert any("DEVICE FAILOVER" in r.getMessage()
               for r in caplog.records)
    assert _sig(stats, c) == ref


def test_no_guard_without_drain_boundaries(tmp_path):
    """checkpoint_save alone (no checkpoint_every / dispatch_segment
    / heartbeat) runs as ONE dispatch segment — no boundary a drain
    could fire at. The guard must NOT install: swallowing SIGTERM
    while promising a drain that can never happen would be strictly
    worse than the default signal disposition."""
    ck = str(tmp_path / "solo.npz")
    stats, c = _run(f"  checkpoint_save: {ck}")
    assert stats.ok
    assert c.runner.guard is None
    # with a boundary source, the guard installs
    stats2, c2 = _run(f"  checkpoint_save: {ck}2\n"
                      f"  dispatch_segment: 400ms")
    assert stats2.ok
    assert c2.runner.guard is not None


# ---------------------------------------------------------------------------
# round-watchdog stall dump (direct unit test of the dump path)
# ---------------------------------------------------------------------------

def test_watchdog_writes_stall_dump_file(tmp_path):
    import time

    from shadow_tpu.core.manager import RoundWatchdog

    cfg = load_config_str(YAML.format(extra="").replace(
        "scheduler_policy: tpu", "scheduler_policy: serial"))
    c = Controller(cfg)          # built, never run: zero progress
    dump_path = str(tmp_path / "stall" / "dump.txt")
    captured = []
    wd = RoundWatchdog(c.manager, 0.1, on_stall=captured.append,
                       dump_path=dump_path)
    wd.start()
    try:
        deadline = time.monotonic() + 5
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert wd.fired
    assert captured and "host left0" in captured[0]
    with open(dump_path) as f:
        text = f.read()
    assert "no progress" in text and "host left0" in text


# ---------------------------------------------------------------------------
# schema validation of the new knobs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("extra,match", [
    ("  checkpoint_every: 100ms", "checkpoint_save"),
    ("  checkpoint_save: /tmp/x.npz\n  checkpoint_every: 100ms\n"
     "  checkpoint_save_time: 1s", "cannot combine"),
    ("  checkpoint_save: /tmp/x.npz\n  checkpoint_every: 100ms\n"
     "  checkpoint_keep: 0", "checkpoint_keep"),
    ("  dispatch_retries: -1", "dispatch_retries"),
    ("  failover: sideways", "failover"),
])
def test_schema_rejects_bad_supervision_knobs(extra, match):
    with pytest.raises(ValueError, match=match):
        load_config_str(YAML.format(extra=extra))


def test_schema_rejects_supervision_on_cpu_policies():
    serial = YAML.replace("scheduler_policy: tpu",
                          "scheduler_policy: serial")
    for extra, match in (("  state_audit: true", "state_audit"),
                         ("  dispatch_retries: 2", "dispatch_retries"),
                         ("  failover: hybrid", "failover")):
        with pytest.raises(ValueError, match=match):
            load_config_str(serial.format(extra=extra))


def test_schema_rejects_hybrid_failover_for_campaigns():
    yaml = YAML.format(extra="  failover: hybrid") + """
ensemble:
  replicas: 2
  vary:
    seed: [1, 2]
"""
    with pytest.raises(ValueError, match="failover"):
        load_config_str(yaml)


# ---------------------------------------------------------------------------
# full mid-campaign preemption of the example sweep (the CI rung, run
# here end-to-end through the gate script)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ensemble_preemption_gate_slow():
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "determinism_gate.py"),
         os.path.join(repo, "examples", "ensemble_seed_sweep.yaml"),
         "--preempt", "--ensemble"],
        cwd=repo, capture_output=True, text=True, timeout=1800)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "preemption OK" in r.stdout
