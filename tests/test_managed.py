"""End-to-end managed-process tests: REAL Linux executables running
under syscall interposition inside the simulation.

The analogue of the reference's add_shadow_tests flow
(src/test/CMakeLists.txt:36-60): compile small C programs, run them as
simulated hosts' processes via a YAML config, and assert on their
stdout — which, because clocks/sleeps/sockets are emulated, is an
exact function of the config (the determinism oracle)."""

import os
import shutil
import subprocess

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

PLUGIN_DIR = os.path.join(os.path.dirname(__file__), "plugins")

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.0 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
]"""


def _indent(text: str, n: int) -> str:
    return "\n".join(" " * n + line for line in text.splitlines())


@pytest.fixture(scope="session")
def plugins(tmp_path_factory):
    """Compile the C test plugins once per session."""
    out = tmp_path_factory.mktemp("plugins")
    bins = {}
    for src in sorted(os.listdir(PLUGIN_DIR)):
        path = os.path.join(PLUGIN_DIR, src)
        if src.endswith("_lib.c"):
            # *_lib.c build as shared objects (dlopen targets)
            name = src[:-2]
            so = out / (name + ".so")
            subprocess.run(
                ["cc", "-O1", "-fPIC", "-shared", "-o", str(so),
                 path],
                check=True, capture_output=True)
            bins[name] = str(so)
        elif src.endswith(".cpp"):
            if shutil.which("g++") is None:
                continue    # test_cpp_runtime skips when absent
            name = src[:-4]
            exe = out / name
            subprocess.run(
                ["g++", "-O1", "-pthread", "-o", str(exe), path],
                check=True, capture_output=True)
            bins[name] = str(exe)
        elif src.endswith(".c"):
            name = src[:-2]
            exe = out / name
            # -ldl AFTER the source: pre-2.34 glibc ships libdl as a
            # separate archive and resolves left-to-right
            subprocess.run(
                ["cc", "-O1", "-pthread", "-o", str(exe), path,
                 "-ldl"],
                check=True, capture_output=True)
            bins[name] = str(exe)
    return bins


def run_sim(yaml_cfg: str, tmp_path) -> tuple:
    cfg = load_config_str(yaml_cfg)
    c = Controller(cfg)
    stats = c.run()
    return stats, os.path.join(str(tmp_path), "shadow.data")


def read_stdout(data_dir: str, host: str, exe: str) -> str:
    d = os.path.join(data_dir, "hosts", host)
    for f in sorted(os.listdir(d)):
        if f.startswith(os.path.basename(exe)) and f.endswith(".stdout"):
            with open(os.path.join(d, f)) as fh:
                return fh.read()
    raise FileNotFoundError(f"no stdout for {exe} in {d}")


def base_cfg(data_dir: str, stop: str = "30s") -> str:
    return f"""
general:
  stop_time: {stop}
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
"""


def test_timecheck_deterministic_clocks(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['timecheck']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "timecheck")
    lines = out.splitlines()
    # clocks are exact simulated values: start 1 s, +100 ms sleep
    assert lines[0] == "t0 1.000000000"
    assert lines[1] == "t1 1.100000000"
    # wall clock = 2000-01-01 epoch offset + sim time
    assert lines[2] == f"wall {946_684_800 + 1}"
    assert lines[3] == "host alice"
    assert lines[4].startswith("pid 10")
    assert stats.ok


def test_udp_ping_echo_between_hosts(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data) + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['udp_echo']}
      args: 9000 3
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['udp_ping']}
      args: 11.0.0.1 9000 3
      start_time: 2s
"""
    stats, _ = run_sim(cfg, tmp_path)
    server_out = read_stdout(data, "server", "udp_echo")
    client_out = read_stdout(data, "client", "udp_ping")
    assert server_out.count("echoed 6 from 11.0.0.2") == 3
    assert "done" in server_out
    for i in range(3):
        assert f"reply {i}: 'ping {i}'" in client_out
    assert "done" in client_out
    # RTT is simulated: 2 x 25 ms path latency (+ sub-ms queuing)
    rtts = [int(line.rsplit("rtt_ms=", 1)[1])
            for line in client_out.splitlines() if "rtt_ms=" in line]
    assert all(50 <= r <= 60 for r in rtts), rtts
    assert stats.packets_delivered >= 6


def test_udp_ping_is_bit_deterministic(plugins, tmp_path):
    outs = []
    for sub in ("a", "b"):
        data = str(tmp_path / sub / "shadow.data")
        cfg = base_cfg(data) + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['udp_echo']}
      args: 9000 2
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['udp_ping']}
      args: 11.0.0.1 9000 2
      start_time: 2s
"""
        run_sim(cfg, tmp_path / sub)
        outs.append(read_stdout(data, "client", "udp_ping")
                    + read_stdout(data, "server", "udp_echo"))
    assert outs[0] == outs[1]


def test_tcp_transfer_is_bit_deterministic(plugins, tmp_path):
    """The reference's determinism gate (src/test/determinism/,
    determinism1_compare.cmake): run the identical config twice and
    byte-compare every host's stdout. TCP exercises the full stack —
    handshake timing, windows, retransmit timers — so any
    nondeterminism (RNG, map ordering, wall-clock leak) shows up."""
    outs = []
    for run in range(2):
        data = str(tmp_path / f"run{run}" / "shadow.data")
        cfg = base_cfg(data, stop="60s") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['tcp_client']}
      args: 11.0.0.1 8080 200000
      start_time: 2s
"""
        stats, _ = run_sim(cfg, tmp_path / f"run{run}")
        assert stats.ok
        outs.append(read_stdout(data, "server", "tcp_server")
                    + read_stdout(data, "client", "tcp_client"))
    assert outs[0] == outs[1]


def test_futex_wait_timeout_advances_sim_time(plugins, tmp_path):
    """FUTEX_WAIT value-mismatch -> EAGAIN; unwaited WAKE -> 0; a 50 ms
    WAIT timeout -> ETIMEDOUT with the simulated monotonic clock
    advanced by exactly 50 ms (futex.c semantics)."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['futex_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    lines = read_stdout(data, "alice", "futex_check").splitlines()
    assert lines[0] == "mismatch: r=-1 errno=11"      # EAGAIN
    assert lines[1] == "wake: r=0"
    assert lines[2] == "wait: r=-1 errno=110 dt_ms=50"  # ETIMEDOUT
    assert stats.ok


def test_pthreads_clone_join_futex(plugins, tmp_path):
    """pthread_create/join under the clone handshake: virtual tids in
    creation order, per-thread simulated nanosleeps, futex-backed
    join, and a contended mutex — all deterministic."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['threads_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "threads_check")
    lines = out.splitlines()
    assert lines[0] == "main tid==pid: 1"
    # each worker slept its simulated interval; tids are main+1..+3
    assert "thread 0 dtid=1 slept=10ms counter=1" in lines
    assert "thread 1 dtid=2 slept=20ms counter=2" in lines
    assert "thread 2 dtid=3 slept=30ms counter=3" in lines
    assert "joined 0 ret=1" in lines
    assert "joined 2 ret=3" in lines
    # main's monotonic clock advanced exactly to the longest sleep
    assert lines[-1] == "all joined: counter=3 elapsed_ms=30"
    assert stats.ok


def test_pthreads_is_bit_deterministic(plugins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        cfg = base_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['threads_check']}
      start_time: 1s
"""
        run_sim(cfg, tmp_path / f"r{run}")
        outs.append(read_stdout(data, "alice", "threads_check"))
    assert outs[0] == outs[1]


def test_sendfile_to_virtual_socket(plugins, tmp_path):
    """sendfile(out=virtual TCP socket, in=real file) streams the file
    through the in-simulator stack; the server's checksum must match.
    260 KB > the send buffer, so the emulation's Blocked/restart
    bookkeeping (no duplicated or dropped spans) is exercised."""
    data = str(tmp_path / "shadow.data")
    nbytes = 260_000
    cfg = base_cfg(data, stop="60s") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['sendfile_client']}
      args: 11.0.0.1 8080 {nbytes}
      start_time: 2s
"""
    stats, _ = run_sim(cfg, tmp_path)
    server_out = read_stdout(data, "server", "tcp_server")
    client_out = read_stdout(data, "client", "sendfile_client")
    sent = [line for line in client_out.splitlines()
            if line.startswith("sendfile sent ")][0].split()
    recv = [line for line in server_out.splitlines()
            if line.startswith("received ")][0].split()
    assert sent[2] == str(nbytes)           # sent all bytes
    assert sent[7] == str(nbytes)           # offset advanced
    assert recv[1] == str(nbytes)
    assert recv[4] == sent[5]               # checksums match
    assert stats.ok


def test_tcp_transfer_checksum(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    nbytes = 300_000          # > the 128 KiB sndbuf: exercises blocking
    cfg = base_cfg(data, stop="60s") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['tcp_client']}
      args: 11.0.0.1 8080 {nbytes}
      start_time: 2s
"""
    stats, _ = run_sim(cfg, tmp_path)
    server_out = read_stdout(data, "server", "tcp_server")
    client_out = read_stdout(data, "client", "tcp_client")
    assert "accepted from 11.0.0.2" in server_out
    assert "connected" in client_out
    sent = [line for line in client_out.splitlines()
            if line.startswith("sent ")][0].split()
    recv = [line for line in server_out.splitlines()
            if line.startswith("received ")][0].split()
    sent_n, sent_sum = sent[1], sent[4]
    recv_n, recv_sum = recv[1], recv[4]
    assert sent_n == str(nbytes)
    assert recv_n == sent_n
    assert recv_sum == sent_sum
    assert stats.ok


def test_strict_traps_mode(plugins, tmp_path):
    """SHADOWTPU_STRICT_TRAPS=1 traps the startup-window syscalls too:
    raw-syscall time reads virtualize (timecheck still sees exact
    simulated clocks) instead of silently reading native values."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['timecheck']}
      environment: SHADOWTPU_STRICT_TRAPS=1
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "timecheck")
    lines = out.splitlines()
    assert lines[0] == "t0 1.000000000"
    assert lines[1] == "t1 1.100000000"
    assert stats.ok


@pytest.fixture(scope="session")
def static_plugin(tmp_path_factory):
    """timecheck compiled -static: no PT_INTERP, LD_PRELOAD inert."""
    out = tmp_path_factory.mktemp("static")
    exe = out / "timecheck_static"
    try:
        subprocess.run(
            ["cc", "-static", "-O1", "-o", str(exe),
             os.path.join(PLUGIN_DIR, "timecheck.c")],
            check=True, capture_output=True)
    except subprocess.CalledProcessError as e:
        pytest.skip(f"no static libc on this machine: "
                    f"{e.stderr.decode(errors='replace')[:200]}")
    return str(exe)


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_readiness_family(plugins, tmp_path, method):
    """The readiness-API family (ref src/test/{epoll,poll,eventfd,
    timerfd,pipe} suites) on both backends: pipe2+poll, eventfd
    counter semantics, timerfd firing through epoll after EXACTLY its
    virtual duration, and a select() timeout consuming exactly its
    simulated 20 ms."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['readiness_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "readiness_check")
    assert "done" in out, out
    for line in out.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[1] in ("0", "1") \
                and not parts[0].endswith("_ms"):
            assert parts[1] == "1", f"{line!r} failed:\n{out}"
    # virtual time is exact: the 30 ms timer and 20 ms select
    # timeout consume precisely their simulated durations
    assert "tfd_wait_ms 30" in out, out
    assert "select_ms 20" in out, out


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_real_cpython_tcp_pair(tmp_path, method):
    """Real, unmodified CPython as the managed application — the
    strongest 'direct execution of real Linux applications' claim we
    can make in-tree. Interpreter startup exercises hundreds of
    syscalls (mmap, openat of the stdlib, getrandom, sigaction,
    epoll via selectors); then a python TCP server and client talk
    across simulated hosts with by-name resolution and EXACT
    simulated RTTs (4 x 25 ms path latency = 100 ms per
    connect+request+reply exchange, deterministic)."""
    import sys as _sys
    data = str(tmp_path / "shadow.data")
    srv = tmp_path / "server.py"
    cli = tmp_path / "client.py"
    srv.write_text(
        "import socket\n"
        "s = socket.socket()\n"
        "s.bind((\"0.0.0.0\", 9000))\n"
        "s.listen(4)\n"
        "for _ in range(2):\n"
        "    c, addr = s.accept()\n"
        "    c.sendall(b\"echo:\" + c.recv(1024))\n"
        "    c.close()\n"
        "print(\"server done\")\n")
    cli.write_text(
        "import socket, time\n"
        "for i in range(2):\n"
        "    t0 = time.monotonic()\n"
        "    c = socket.create_connection((\"server\", 9000))\n"
        "    c.sendall(f\"msg{i}\".encode())\n"
        "    r = c.recv(1024)\n"
        "    rtt = time.monotonic() - t0\n"
        "    print(f\"got {r.decode()} rtt={rtt*1000:.0f}ms\")\n"
        "    c.close()\n"
        "print(\"client done\")\n")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  server:
    network_node_id: 0
    processes:
    - {{path: {_sys.executable}, args: {srv}, start_time: 1s}}
  client:
    network_node_id: 1
    processes:
    - {{path: {_sys.executable}, args: {cli}, start_time: 2s}}
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    srv_out = read_stdout(data, "server", "python")
    cli_out = read_stdout(data, "client", "python")
    assert "server done" in srv_out, srv_out
    assert "got echo:msg0 rtt=100ms" in cli_out, cli_out
    assert "got echo:msg1 rtt=100ms" in cli_out, cli_out
    assert "client done" in cli_out, cli_out


def _wget_block() -> str:
    """An extra wget process entry when wget exists (it drives its
    socket with select(), exercising that path with a production
    binary)."""
    w = shutil.which("wget")
    if w is None:
        return ""
    return (f"\n    - {{path: {w}, "
            f"args: -q -O got.html http://www:8080/,\n"
            f"       start_time: 4s}}")


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_real_curl_fetches_real_http_server(tmp_path, method):
    """The reference README's marquee claim, reproduced: real curl
    downloads over HTTP from a real `python -m http.server` across
    the simulated network — two unmodified production binaries
    (libcurl's nonblocking state machine + CPython's socketserver)
    speaking real HTTP through the emulated TCP stack."""
    import shutil as _shutil
    import sys as _sys
    curl = _shutil.which("curl")
    if curl is None:
        pytest.skip("no curl on this machine")
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  www:
    network_node_id: 0
    processes:
    - {{path: {_sys.executable},
       args: -m http.server 8080 --bind 0.0.0.0, start_time: 1s}}
  fetcher:
    network_node_id: 1
    processes:
    - {{path: {curl}, args: -s -o fetched.html http://www:8080/,
       start_time: 3s}}{_wget_block()}
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = os.path.join(data, "hosts", "fetcher", "fetched.html")
    assert os.path.exists(out), os.listdir(
        os.path.join(data, "hosts", "fetcher"))
    body = open(out).read()
    assert "Directory listing" in body or "<html" in body.lower()
    wgot = os.path.join(data, "hosts", "fetcher", "got.html")
    if _wget_block():
        assert os.path.exists(wgot)
        assert open(wgot).read() == body   # same listing, both tools


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_fd_window_emfile_and_recycling(plugins, tmp_path, method):
    """The [600, 1024) virtual fd window: EMFILE exactly at the
    424-slot capacity, kernel-style lowest-free allocation, freed
    slots recycle (the monotonic-cursor bug this pins would have
    exhausted the window after 424 cumulative opens forever)."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['fdlimit_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "fdlimit_check")
    assert "emfile 1" in out, out
    assert "capacity 424" in out, out
    assert "floor 600" in out, out
    assert "reopen 1" in out, out
    assert "lowest_free 1" in out, out
    assert "drain_reopen 1" in out, out
    assert "rlimit_virtual_default 1" in out, out
    assert "setrlimit 1" in out, out
    assert "rlimit_roundtrip 1" in out, out
    assert "done" in out, out


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_socketpair_family(plugins, tmp_path, method):
    """socketpair(AF_UNIX) on both backends (ref dispatch parity):
    DGRAM message boundaries, a STREAM pair shared across fork with
    request/reply + EOF on child exit, and shutdown(SHUT_WR)
    half-close semantics (peer EOF, writer EPIPE, reverse direction
    stays open)."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['socketpair_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "socketpair_check")
    assert "done" in out, out
    for line in out.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[1] in ("0", "1"):
            assert parts[1] == "1", f"{line!r} failed:\n{out}"


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_cpp_runtime(plugins, tmp_path, method):
    """C++ runtime under both backends (ref src/test/cpp): libstdc++
    static init, exceptions, std::string, std::thread (clone), and
    std::chrono steady_clock + sleep_for on the VIRTUAL clock."""
    if "cpp_check" not in plugins:
        pytest.skip("no g++ on this machine")
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['cpp_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "cpp_check")
    assert "str cpp-eh" in out, out
    assert "thread 42" in out, out
    assert "sleep_visible 1" in out, out
    assert "done" in out, out


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_dynlink_dlopen(plugins, tmp_path, method):
    """Runtime dynamic linking under both backends (ref
    src/test/dynlink): dlopen + dlsym work, and the dlopened
    library's clock reads sit on the main image's virtual timeline
    (interposition is process-wide)."""
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['dynlink_check']}
      args: {plugins['dyn_target_lib']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "dynlink_check")
    for want in ("dlopen 1", "dlsym 1", "add 42", "monotonic 1",
                 "sleep_visible 1", "done"):
        assert want in out, out


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_static_binary_interposition(static_plugin, tmp_path, method):
    """A statically linked binary runs under BOTH configured backends
    with fully virtualized clocks: under ptrace directly (every
    syscall traps, vDSO patched), and under preload via the automatic
    static-ELF fallback to ptrace (LD_PRELOAD cannot enter a static
    image — ref shim.c:393-506's dynamic-only injection)."""
    from shadow_tpu.host.process import elf_is_static
    assert elf_is_static(static_plugin)
    data = str(tmp_path / "shadow.data")
    cfg = base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {static_plugin}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "timecheck_static")
    lines = out.splitlines()
    assert lines[0] == "t0 1.000000000"
    assert lines[1] == "t1 1.100000000"
    assert lines[3] == "host alice"
    assert lines[4].startswith("pid 10")    # virtual pid space


@pytest.mark.parametrize("mode", ["strict_preload", "ptrace"])
def test_raw_syscalls_virtualized(plugins, tmp_path, mode):
    """Raw syscall(2) users of the startup-window set (the static/
    musl/Go pattern) are fully virtualized under strict-traps preload
    AND under ptrace: simulated clocks, virtual pid, deterministic
    randomness — bit-identical across runs."""
    outs = []
    for run in range(2):
        data = str(tmp_path / f"{mode}{run}" / "shadow.data")
        cfg = base_cfg(data)
        if mode == "ptrace":
            cfg = cfg.replace(
                "hosts:\n",
                "experimental:\n  interpose_method: ptrace\nhosts:\n")
            env = ""
        else:
            env = "\n      environment: SHADOWTPU_STRICT_TRAPS=1"
        cfg += f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['rawsys_check']}{env}
      start_time: 1s
"""
        stats, _ = run_sim(cfg, tmp_path / f"{mode}{run}")
        assert stats.ok
        out = read_stdout(data, "alice", "rawsys_check")
        lines = out.splitlines()
        assert lines[0] == "raw_clock 0 1.000000000", out
        # raw time(2) reads the simulated wall clock (epoch offset)
        assert lines[1].startswith("raw_time ")
        assert int(lines[1].split()[1]) < 1_700_000_000
        assert int(lines[2].split()[1]) >= 1000     # virtual pid
        assert lines[3].startswith("raw_rand 8 ")
        assert lines[4] == "done"
        outs.append(out)
    assert outs[0] == outs[1]
