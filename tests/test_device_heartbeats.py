"""Device-path observability: per-host heartbeat CSVs + perf summary.

The device program pauses at heartbeat boundaries (stop is a runtime
scalar; window clamping stays on the global horizon), emits
[shadow-heartbeat] [node] lines from device counters, and resumes —
and the segmentation must NOT perturb the trace (bit-identical
checksums vs an unsegmented run).
"""

import logging

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

YAML = """
general:
  stop_time: 2s
  seed: 5
  {hb}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.01 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ] ]
experimental:
  scheduler_policy: tpu
hosts:
  left:
    quantity: 4
    network_node_id: 0
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 4
    network_node_id: 1
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


def _run(hb: str):
    c = Controller(load_config_str(YAML.format(hb=hb)))
    stats = c.run()
    return stats, [h.trace_checksum for h in c.sim.hosts]


def test_device_heartbeats_emitted_and_trace_preserved(caplog):
    with caplog.at_level(logging.INFO):
        s_hb, chk_hb = _run("heartbeat_interval: 500ms")
    lines = [r.getMessage() for r in caplog.records
             if "[shadow-heartbeat] [node]" in r.getMessage()]
    # 8 hosts x 3 interior boundaries (0.5, 1.0, 1.5 s)
    assert len(lines) == 24, lines[:5]
    header = [r.getMessage() for r in caplog.records
              if "[node-header]" in r.getMessage()]
    assert header, "heartbeat header row missing"
    # counters are nonzero by the first boundary
    assert any(",left0," in ln or "left0" in ln for ln in lines)
    perf = [r.getMessage() for r in caplog.records
            if "device perf:" in r.getMessage()]
    assert perf and "rounds" in perf[0]

    # the events column is a per-interval DELTA, not cumulative: one
    # host's interval values must sum to at most its run total
    left0 = [ln.split("[node] ")[1].split(",") for ln in lines
             if ln.split("[node] ")[1].split(",")[1] == "left0"]
    assert len(left0) == 3
    deltas = [int(row[2]) for row in left0]
    assert all(d >= 0 for d in deltas)
    assert sum(deltas) <= s_hb.events_executed

    s_plain, chk_plain = _run("")
    assert s_hb.ok and s_plain.ok
    assert s_hb.events_executed == s_plain.events_executed
    assert s_hb.rounds == s_plain.rounds
    assert chk_hb == chk_plain      # segmentation is trace-invisible
