"""Name resolution + preload-mode TSC emulation for managed processes.

Round-3 closure of two determinism/fidelity gaps: managed programs can
now resolve simulated hostnames (shim getaddrinfo/gethostname/
getifaddrs overrides reading the simulator's hosts file — reference
preload_libraries.c:30-120 + dns.c), and rdtsc/rdtscp in PRELOAD mode
are trapped via PR_SET_TSC and synthesized from simulated time
(reference lib/tsc/tsc.c — previously only the ptrace backend did
this, so a preload plugin reading TSC silently broke determinism).
"""

import os
import subprocess

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

PLUGIN_DIR = os.path.join(os.path.dirname(__file__), "plugins")

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.0 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
]"""


def _indent(text: str, n: int) -> str:
    return "\n".join(" " * n + line for line in text.splitlines())


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("plugins")
    built = {}
    for name in ("resolver_check", "rdtsc_check", "tcp_server",
                 "segv_chain_check", "rand_check"):
        exe = out / name
        subprocess.run(
            ["cc", "-O1", "-pthread", "-o", str(exe),
             os.path.join(PLUGIN_DIR, f"{name}.c"), "-ldl"],
            check=True, capture_output=True)
        built[name] = str(exe)
    return built


def run_sim(hosts_yaml: str, data: str, stop: str = "30s"):
    cfg = load_config_str(f"""
general:
  stop_time: {stop}
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
{hosts_yaml}
""")
    return Controller(cfg).run()


def stdout_of(data: str, host: str, exe: str) -> str:
    d = os.path.join(data, "hosts", host)
    for f in sorted(os.listdir(d)):
        if f.startswith(exe) and f.endswith(".stdout"):
            with open(os.path.join(d, f)) as fh:
                return fh.read()
    raise FileNotFoundError(f"no stdout for {exe} in {d}")


def test_managed_process_resolves_simulated_names(bins, tmp_path):
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  server:
    network_node_id: 0
    processes:
    - path: {bins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {bins['resolver_check']}
      args: server 8080
      start_time: 2s
""", data)
    assert stats.ok
    out = stdout_of(data, "client", "resolver_check").splitlines()
    assert out[0] == "hostname client"
    # DNS assigns 11.0.0.x in registration order: server first
    assert out[1] == "resolved server 11.0.0.1:8080"
    assert out[2] == "unknown rc==EAI_NONAME 1"
    assert out[3] == "self 11.0.0.2"
    assert out[4] == "if lo 127.0.0.1"
    assert out[5] == "if eth0 11.0.0.2"
    assert out[6] == "connected wrote 13"


def test_preload_rdtsc_is_simulated_time(bins, tmp_path):
    """rdtsc in preload mode: cycles == simulated ns at the nominal
    1 GHz, so a 50 ms usleep reads as exactly 50,000,000 cycles."""
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  alice:
    network_node_id: 0
    processes:
    - path: {bins['rdtsc_check']}
      start_time: 1s
""", data)
    assert stats.ok
    out = stdout_of(data, "alice", "rdtsc_check").splitlines()
    # t0 = 1 s sim = 1e9 cycles at boot of the process
    assert out[0] == "t0 1000000000"
    assert out[1] == "dt 50000000"
    assert out[2] == "p_ge 1"


def test_rand_bytes_deterministic(bins, tmp_path):
    """getrandom AND the shim's OpenSSL RAND_bytes override draw from
    the seeded per-host stream: byte-identical across two runs of the
    same seed (the reference's openssl_preload determinism role)."""
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        stats = run_sim(f"""
  alice:
    network_node_id: 0
    processes:
    - path: {bins['rand_check']}
      start_time: 1s
""", data)
        assert stats.ok
        outs.append(stdout_of(data, "alice", "rand_check"))
    assert outs[0] == outs[1]
    lines = outs[0].splitlines()
    # the override actually bound AND produced hex (not the
    # "randbytes unavailable" fallback)
    assert lines[1].startswith("randbytes ")
    draw = lines[1].split()[1]
    assert len(draw) == 16 and int(draw, 16) >= 0
    # two independent draws from one stream must differ
    assert lines[0].split()[1] != draw


def test_app_sigsegv_handler_chains_with_tsc(bins, tmp_path):
    """An app-installed SIGSEGV handler (Go/JVM-style) must not break
    TSC emulation, and real faults must reach the app's handler."""
    data = str(tmp_path / "shadow.data")
    stats = run_sim(f"""
  alice:
    network_node_id: 0
    processes:
    - path: {bins['segv_chain_check']}
      start_time: 1s
""", data)
    assert stats.ok
    out = stdout_of(data, "alice", "segv_chain_check").splitlines()
    assert out[0] == "dt 20000000"      # rdtsc emulated: 20 ms sim
    assert out[1] == "faults 1"         # real fault chained to the app
    assert out[2] == "t2_ge 1"          # emulation survives the chain
