"""Elastic mesh-shrink failover + the deterministic chaos injector
(device/chaos.py, failover: shrink, capacity.reshard_state).

The contract under test: losing 1 of N mesh devices mid-run costs
1/N of throughput, never the run or the trace — a scripted device
loss exhausts retries, the last validated state re-shards onto the
survivors (new padded width, re-planned exchange capacities, warm
engine rebuild), and the continuation is bit-identical to both the
uninterrupted M-shard run and the serial oracle. Checkpoints written
after the shrink stamp the new geometry and resume on it
automatically. Campaigns get the same ladder (the replica axis vmaps
outside the mesh axis). Every injected fault fires at a
deterministic seam counter, so runs reproduce byte for byte,
failures included.
"""

import logging
import os

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import chaos as chaosmod
from shadow_tpu.device import checkpoint, supervise

YAML = """
general:
  stop_time: 800ms
  seed: 9
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""

SHRINK = """  mesh_shards: 4
  dispatch_segment: 200ms
  state_audit: true
  failover: shrink
  dispatch_retries: 1
  dispatch_retry_backoff: 0.0
  chaos:
  - {kind: device_loss, segment: 2, shard: 1}
"""


def _run(extra=""):
    c = Controller(load_config_str(YAML.format(extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


@pytest.fixture(scope="module")
def ref():
    """The uninterrupted reference signature, computed ONCE on a
    3-shard mesh: per-host signatures are invariant across mesh
    shape, segmentation cadence, audit, and pipeline depth (the
    determinism contract, pinned elsewhere), so every recovery test
    in this module compares against this one run."""
    stats, c = _run("  mesh_shards: 3\n"
                    "  dispatch_segment: 200ms\n"
                    "  state_audit: true")
    assert stats.ok
    return _sig(stats, c)


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("extra,match", [
    ("  chaos:\n  - {kind: sideways, segment: 1}", "kind"),
    ("  chaos:\n  - {kind: device_loss, segment: 1}", "shard"),
    ("  chaos:\n  - {kind: dispatch_error}", "segment"),
    ("  chaos:\n  - {kind: checkpoint_corrupt}", "entry"),
    ("  chaos:\n  - {kind: cache_store_fail}", "store"),
    ("  chaos:\n  - {kind: cache_store_fail, store: 0, shard: 1}",
     "not valid"),
    ("  mesh_shards: -1", "mesh_shards"),
])
def test_schema_rejects_bad_chaos_knobs(extra, match):
    with pytest.raises(ValueError, match=match):
        load_config_str(YAML.format(extra=extra))


def test_schema_rejects_chaos_on_cpu_policies():
    serial = YAML.replace("scheduler_policy: tpu",
                          "scheduler_policy: serial")
    for extra, match in (
            ("  chaos:\n  - {kind: cache_store_fail, store: 0}",
             "chaos"),
            ("  mesh_shards: 2", "mesh_shards")):
        with pytest.raises(ValueError, match=match):
            load_config_str(serial.format(extra=extra))


def test_schema_allows_shrink_for_campaigns_rejects_hybrid(tmp_path):
    ens = ENS.format(rec=tmp_path / "ENSEMBLE.json")
    cfg = load_config_str(YAML.format(extra="  failover: shrink")
                          + ens)
    assert cfg.experimental.failover == "shrink"
    with pytest.raises(ValueError, match="shrink"):
        load_config_str(YAML.format(extra="  failover: hybrid") + ens)


# ---------------------------------------------------------------------------
# the tentpole: scripted device loss -> 4 -> 3 shrink, bit-identical
# ---------------------------------------------------------------------------

def test_shrink_bitmatches_uninterrupted_3_shard_run(ref):
    stats, c = _run(SHRINK)
    assert stats.ok
    assert stats.reshards == 1
    assert stats.retries >= 1
    assert c.runner.engine.n_shards == 3
    assert _sig(stats, c) == ref
    # the injector's ledger names what fired, deterministically
    assert [f["kind"] for f in c.runner.chaos.fired] == ["device_loss"]
    # the audited run kept a zero health word across the reshard
    assert int(np.asarray(c.runner.final_state["aud"]).max()) == 0


def test_shrink_checkpoints_stamp_geometry_and_resume(tmp_path,
                                                     ref):
    base = str(tmp_path / "ck.npz")
    stats, c = _run(SHRINK + f"  checkpoint_save: {base}\n"
                             f"  checkpoint_every: 200ms\n"
                             f"  checkpoint_keep: 8")
    assert stats.ok and stats.reshards == 1
    entries = supervise.rotation_entries(base)
    post = [(t, p) for t, p in entries if t < 800_000_000]
    assert post, "no rotation entry before stop"
    t_last, p_last = post[-1]
    geom = checkpoint.peek_geometry(checkpoint.peek_meta(p_last))
    # a post-shrink checkpoint stamps the SHRUNKEN geometry
    assert geom == {"n_shards": 3, "h_pad": 6, "h_loc": 2}

    # resume on the full (8-device conftest) pool: the runner must
    # adopt the saved 3-shard geometry from the stamp and bit-match
    res_stats, res_c = _run(f"  checkpoint_load: {p_last}\n"
                            f"  dispatch_segment: 200ms")
    assert res_stats.ok
    assert res_c.runner.engine.n_shards == 3
    assert _sig(res_stats, res_c) == ref


def test_geometry_mismatch_message_is_readable(tmp_path):
    """Satellite: the shard-geometry fields live in readable
    __meta__ keys, so a direct cross-geometry load names the shard
    counts instead of an opaque fingerprint diff."""
    base = str(tmp_path / "geo.npz")
    stats, c = _run("  mesh_shards: 4\n"
                    f"  checkpoint_save: {base}\n"
                    "  checkpoint_save_time: 400ms")
    assert stats.ok
    meta = checkpoint.peek_meta(base)
    assert meta["geometry"] == {"n_shards": 4, "h_pad": 8,
                               "h_loc": 2}
    # build (never run) a 2-shard engine and load the 4-shard
    # checkpoint directly: the refusal must name the shard counts
    cfg2 = load_config_str(YAML.format(extra="  mesh_shards: 2"))
    c2 = Controller(cfg2)
    with pytest.raises(ValueError,
                       match=r"saved on 4 shard\(s\).*loading on 2"):
        checkpoint.load_state(c2.runner.engine, c2.sim.starts, base,
                              final_stop=800_000_000)


def test_reshard_state_rejects_unregistered_leaves():
    _, c = _run("  mesh_shards: 2")
    from shadow_tpu._jax import jax
    from shadow_tpu.device import capacity

    r = c.runner
    state = jax.device_get(r.engine.init_state(r.sim.starts))
    template = dict(state)
    template["mystery"] = np.zeros(8)
    bad = dict(state)
    bad["mystery"] = np.zeros(8)
    with pytest.raises(ValueError, match="mystery"):
        capacity.reshard_state(bad, 6, template)
    # a snapshot carrying a non-auxiliary leaf the target lacks is
    # equally loud
    with pytest.raises(ValueError, match="mystery"):
        capacity.reshard_state(bad, 6, state)


def test_shrink_composes_with_pipelined_dispatch(ref):
    """A device loss under a depth-4 pipeline window: the issue-time
    error is held until the segments issued before it drain (they
    were dispatched against the live mesh and are valid — exactly
    when the serial loop would observe the failure), then the window
    replays on the shrunken mesh — PR 11's recovery rule composed
    with the reshard, bit-identical throughout."""
    stats, c = _run(SHRINK.replace("dispatch_segment: 200ms",
                                   "dispatch_segment: 100ms")
                    + "  pipeline_depth: 4\n")
    assert stats.ok and stats.reshards == 1
    assert c.runner.engine.n_shards == 3
    assert _sig(stats, c) == ref
    assert stats.pipeline["depth"] == 4
    assert stats.pipeline["max_in_flight"] >= 2


# ---------------------------------------------------------------------------
# the other chaos kinds
# ---------------------------------------------------------------------------

def test_one_shot_dispatch_error_retries_bitmatch(ref):
    stats, c = _run(
        "  dispatch_segment: 200ms\n"
        "  dispatch_retries: 2\n"
        "  dispatch_retry_backoff: 0.0\n"
        "  chaos:\n"
        "  - {kind: dispatch_error, segment: 1, "
        "error: RESOURCE_EXHAUSTED}")
    assert stats.ok
    assert stats.retries == 1 and stats.reshards == 0
    assert _sig(stats, c) == ref

    # a non-transient scripted class is never retried
    with pytest.raises(chaosmod.ChaosError, match="INVALID_ARGUMENT"):
        _run("  dispatch_segment: 200ms\n"
             "  dispatch_retries: 5\n"
             "  chaos:\n"
             "  - {kind: dispatch_error, segment: 1, "
             "error: INVALID_ARGUMENT}")


def test_checkpoint_corrupt_engages_newest_readable(tmp_path):
    base = str(tmp_path / "rot.npz")
    # 3 rotation saves (200ms cadence, stop 800ms => t=200/400/600);
    # the schedule corrupts the LAST one
    stats, _ = _run(f"  checkpoint_save: {base}\n"
                    f"  checkpoint_every: 200ms\n"
                    f"  checkpoint_keep: 8\n"
                    f"  dispatch_segment: 200ms\n"
                    f"  chaos:\n"
                    f"  - {{kind: checkpoint_corrupt, entry: 2}}")
    assert stats.ok
    entries = supervise.rotation_entries(base)
    newest = entries[-1][1]
    # the end-of-run base save would win resolution; drop it to
    # simulate the crash the rotation exists for
    os.unlink(base)
    resolved = supervise.resolve_checkpoint(base)
    assert resolved != newest
    assert resolved == entries[-2][1]
    with pytest.raises(Exception):
        checkpoint.peek_meta(newest)


def test_cache_store_fail_degrades_loudly(tmp_path, caplog):
    # a fresh cache directory: the session-shared test cache would
    # serve a HIT and no store (the drilled seam) would ever fire
    with caplog.at_level(logging.WARNING):
        stats, c = _run("  chaos:\n"
                        "  - {kind: cache_store_fail, store: 0}\n"
                        f"  compile_cache: {tmp_path / 'aot'}")
    assert stats.ok
    inj = c.runner.chaos
    rep = stats.compile_cache or {}
    if rep.get("unsupported"):
        pytest.skip("backend has no executable serialization — no "
                    "store seam to drill")
    assert [f["kind"] for f in inj.fired] == ["cache_store_fail"]
    assert any("refused by the chaos schedule" in r.getMessage()
               for r in caplog.records)


def test_injector_not_leaked_across_runs():
    stats, c = _run("  chaos:\n"
                    "  - {kind: cache_store_fail, store: 999}")
    assert c.runner.chaos is not None
    _run("")
    assert chaosmod.current() is None


# ---------------------------------------------------------------------------
# ensemble campaigns shrink too (their first working failover)
# ---------------------------------------------------------------------------

ENS = """
ensemble:
  replicas: 2
  vary:
    seed: [9, 11]
  record_path: {rec}
"""


def test_ensemble_campaign_shrinks_bitmatch(tmp_path):
    def run_ens(extra):
        ens = ENS.format(rec=tmp_path / "ENSEMBLE.json")
        c = Controller(load_config_str(YAML.format(extra=extra)
                                       + ens))
        stats = c.run()
        f = c.runner.final_state
        return stats, c, {k: np.asarray(f[k])
                          for k in ("chk", "n_exec", "n_sent",
                                    "n_drop", "n_deliv")}

    ref_stats, _, ref = run_ens("  mesh_shards: 3\n"
                                "  dispatch_segment: 200ms\n"
                                "  state_audit: true")
    assert ref_stats.ok
    stats, c, f = run_ens(SHRINK)
    assert stats.ok
    assert stats.reshards == 1
    assert c.runner.engine.n_shards == 3
    H = 6
    for k in ref:
        assert np.array_equal(ref[k][:, :H], f[k][:, :H]), k


# ---------------------------------------------------------------------------
# satellite: persist failure during escalation still fails over, with
# ONE diagnostic naming the persist error
# ---------------------------------------------------------------------------

def test_failover_persist_failure_still_runs_hybrid(monkeypatch,
                                                    caplog, ref):
    import shadow_tpu.device.engine as eng

    def dead(self, state, stop=None, final_stop=None):
        raise RuntimeError("UNAVAILABLE: device went away")

    def unsavable(engine, state, path, sim_time, **kw):
        raise OSError("disk full: injected persist failure")

    monkeypatch.setattr(eng.DeviceEngine, "run", dead)
    monkeypatch.setattr(checkpoint, "save_state", unsavable)
    with caplog.at_level(logging.ERROR):
        stats, c = _run("  failover: hybrid\n"
                        "  dispatch_segment: 200ms")
    assert stats.ok
    # no state made it to disk: the stat says so explicitly
    assert stats.failover_checkpoint == ""
    assert _sig(stats, c) == ref
    diags = [r.getMessage() for r in caplog.records
             if "DEVICE FAILOVER" in r.getMessage()]
    assert len(diags) == 1, diags
    assert "injected persist failure" in diags[0]
    assert "NO device-side resume point" in diags[0]


def test_failed_reshard_rolls_back_before_escalating(monkeypatch,
                                                     tmp_path, ref):
    """A shrink that dies mid-reshard must roll the runner back to
    the OLD mesh/engine before escalating: the escalation persists
    the (old-geometry) snapshot through runner.engine, so a
    half-committed shrink would stamp the new geometry over
    old-layout leaves and poison the failover checkpoint."""
    from shadow_tpu.device import capacity

    def broken_reshard(host_state, n_hosts, template_host):
        raise RuntimeError("injected reshard failure")

    monkeypatch.setattr(capacity, "reshard_state", broken_reshard)
    base = str(tmp_path / "fo.npz")
    stats, c = _run(SHRINK + f"  checkpoint_save: {base}\n")
    # shrink failed -> the ladder's hybrid rung finished the run
    assert stats.ok
    assert stats.reshards == 0
    assert _sig(stats, c) == ref
    assert stats.failover_checkpoint
    geom = checkpoint.peek_geometry(
        checkpoint.peek_meta(stats.failover_checkpoint))
    # the failover checkpoint carries the ORIGINAL 4-shard geometry,
    # matching its leaves — not the half-committed 3-shard mesh
    assert geom["n_shards"] == 4


def test_shrink_escalates_to_hybrid_when_nothing_dead(monkeypatch,
                                                      caplog, ref):
    """The ladder: failover: shrink with a dispatch failure no
    liveness probe can attribute (every device answers) must fall
    through to the hybrid rung, not abort."""
    import shadow_tpu.device.engine as eng

    def dead(self, state, stop=None, final_stop=None):
        raise RuntimeError("UNAVAILABLE: flaky fabric, no dead chip")

    monkeypatch.setattr(eng.DeviceEngine, "run", dead)
    with caplog.at_level(logging.ERROR):
        stats, c = _run("  failover: shrink\n"
                        "  dispatch_segment: 200ms")
    assert stats.ok
    assert _sig(stats, c) == ref
    assert any("cannot be attributed" in r.getMessage()
               for r in caplog.records)
    assert any("DEVICE FAILOVER" in r.getMessage()
               for r in caplog.records)
