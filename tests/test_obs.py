"""Flight recorder (shadow_tpu/obs): tracer mechanics, the Perfetto
export format, per-phase wall attribution, the streamed JSONL
artifact, trace_report aggregation, watchdog span embedding, and the
end-to-end bit-identity contract (telemetry off == summary == trace).
"""

import json
import logging
import os
import time

import pytest

from shadow_tpu.obs.trace import (
    NullTracer,
    PHASES,
    RECENT_SPANS,
    Tracer,
    current,
    set_current,
)


# ---------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------

def test_schema_validates_telemetry():
    from shadow_tpu.config.schema import ExperimentalOptions

    out = ExperimentalOptions.from_dict({})
    assert out.telemetry == "summary"
    assert out.telemetry_path == ""
    out = ExperimentalOptions.from_dict({"telemetry": "trace",
                                         "telemetry_path": "/tmp/x"})
    assert out.telemetry == "trace"
    with pytest.raises(ValueError, match="telemetry"):
        ExperimentalOptions.from_dict({"telemetry": "verbose"})


# ---------------------------------------------------------------------
# tracer mechanics
# ---------------------------------------------------------------------

def test_span_walls_and_recent():
    tr = Tracer(mode="summary")
    with tr.span("dispatch", "dispatch", sim_t0=0, sim_t1=100) as sp:
        sp.add(rounds=3)
        time.sleep(0.01)
    tr.instant("preempt.request", "checkpoint", sim_t0=50)
    walls = tr.phase_walls(total_wall_s=1.0)
    assert walls["dispatch_s"] >= 0.01
    assert walls["checkpoint_s"] == 0.0
    # host is the residual of the given total
    assert walls["host_s"] == pytest.approx(
        1.0 - sum(v for k, v in walls.items() if k != "host_s"),
        abs=1e-6)
    recent = tr.recent()
    assert [r["name"] for r in recent] == ["dispatch",
                                           "preempt.request"]
    assert recent[0]["args"]["rounds"] == 3
    assert recent[0]["sim_t0"] == 0 and recent[0]["sim_t1"] == 100
    text = tr.format_recent()
    assert "dispatch" in text and "preempt.request" in text


def test_self_time_attribution():
    # a nested record (the AOT compile inside the first dispatch)
    # must not be double-counted: the outer span's bucket gets only
    # its self time, so the buckets sum to at most the elapsed wall
    tr = Tracer(mode="summary")
    with tr.span("dispatch", "dispatch"):
        time.sleep(0.06)                     # "the compile elapses
        tr.record("aot.compile:run", "compile", 0.05)  # in here"
        with tr.span("inner.save", "checkpoint"):
            time.sleep(0.02)
    walls = tr._walls
    assert walls["compile"] == pytest.approx(0.05, abs=0.01)
    assert walls["checkpoint"] >= 0.02
    # the dispatch bucket got gross - (compile + checkpoint), NOT
    # the gross ~0.08s
    assert walls["dispatch"] < walls["compile"] + walls["checkpoint"]
    # the record keeps the GROSS duration plus self_s
    rec = tr.recent()[-1]
    assert rec["name"] == "dispatch"
    assert rec["dur_s"] >= 0.08
    assert rec["self_s"] == pytest.approx(
        rec["dur_s"] - 0.05 - walls["checkpoint"], abs=0.01)


def test_span_error_tagged_and_reraised():
    tr = Tracer(mode="summary")
    with pytest.raises(RuntimeError):
        with tr.span("dispatch", "dispatch"):
            raise RuntimeError("transient")
    rec = tr.recent()[-1]
    assert rec["args"]["error"] == "RuntimeError"


def test_recent_ring_bounded():
    tr = Tracer(mode="summary")
    for i in range(RECENT_SPANS + 10):
        tr.instant(f"tick{i}", "host")
    recent = tr.recent()
    assert len(recent) == RECENT_SPANS
    assert recent[-1]["name"] == f"tick{RECENT_SPANS + 9}"


def test_null_tracer_is_inert(tmp_path):
    tr = NullTracer()
    with tr.span("x", "dispatch") as sp:
        sp.add(rounds=1)
    tr.instant("y")
    tr.record("z", "compile", 1.0)
    assert tr.recent() == []
    assert tr.phase_walls() == {}
    assert tr.finalize() is None


def test_current_tracer_swap():
    tr = Tracer(mode="summary")
    old = current()
    try:
        set_current(tr)
        assert current() is tr
        set_current(None)
        assert isinstance(current(), NullTracer)
    finally:
        set_current(old)


# ---------------------------------------------------------------------
# artifacts: JSONL stream, Perfetto export, METRICS record
# ---------------------------------------------------------------------

def test_trace_mode_writes_all_artifacts(tmp_path):
    tr = Tracer(mode="trace", directory=str(tmp_path), label="t_9")
    with tr.span("dispatch", "dispatch", sim_t0=0, sim_t1=10):
        time.sleep(0.002)
    tr.instant("occ.save", "plan", path="x.json")
    summary = tr.finalize(run_info={"policy": "tpu"},
                          counters={"events": 5})
    # idempotent
    assert tr.finalize() is summary

    jsonl = tmp_path / "TRACE_t_9.jsonl"
    assert jsonl.exists()
    recs = [json.loads(ln) for ln in
            jsonl.read_text().strip().splitlines()]
    assert [r["name"] for r in recs] == ["dispatch", "occ.save"]
    assert not list(tmp_path.glob("*.partial"))

    trace = json.loads((tmp_path / "TRACE_t_9.trace.json")
                       .read_text())
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs}
    assert "dispatch" in names and "occ.save" in names
    # every phase has a named swimlane
    lanes = {e["args"]["name"] for e in evs
             if e["name"] == "thread_name"}
    assert set(PHASES) <= lanes
    x = [e for e in evs if e["name"] == "dispatch"][0]
    assert x["ph"] == "X" and x["dur"] > 0
    assert x["args"]["sim_t1_ns"] == 10
    i = [e for e in evs if e["name"] == "occ.save"][0]
    assert i["ph"] == "i"

    metrics = json.loads((tmp_path / "METRICS_t_9.json").read_text())
    assert metrics["run"]["policy"] == "tpu"
    assert metrics["counters"]["events"] == 5
    # the per-phase walls sum to the recorded total (the acceptance
    # contract, exact by the residual construction)
    assert sum(metrics["phases"].values()) == pytest.approx(
        metrics["total_wall_s"], rel=0.01, abs=0.01)
    assert metrics["files"]["jsonl"].endswith("TRACE_t_9.jsonl")


def test_summary_mode_writes_metrics_only_with_path(tmp_path):
    tr = Tracer(mode="summary", directory=str(tmp_path), label="s_1")
    tr.instant("x", "host")
    tr.finalize()
    assert (tmp_path / "METRICS_s_1.json").exists()
    assert not (tmp_path / "TRACE_s_1.jsonl").exists()
    assert not (tmp_path / "TRACE_s_1.trace.json").exists()


def test_streamed_lines_atomic_placement(tmp_path):
    from shadow_tpu.utils.artifacts import StreamedLines

    path = str(tmp_path / "log.jsonl")
    s = StreamedLines(path, flush_every=1)
    s.write_line('{"a":1}')
    assert not os.path.exists(path)          # still streaming
    assert os.path.exists(s.partial)
    assert open(s.partial).read() == '{"a":1}\n'
    assert s.close() == path
    assert open(path).read() == '{"a":1}\n'
    assert not os.path.exists(s.partial)

    s2 = StreamedLines(path + "2")
    s2.write_line("x")
    kept = s2.abandon()                      # error path keeps it
    assert os.path.exists(kept)


def test_non_serializable_args_degrade_not_crash(tmp_path):
    # span args are free-form kwargs from a dozen call sites; a
    # stray numpy scalar must degrade to its string form on every
    # write path, never abort the run (the recorder's contract)
    import numpy as np

    tr = Tracer(mode="trace", directory=str(tmp_path), label="np_1")
    with tr.span("dispatch", "dispatch", weird=np.int64(7),
                 arr=np.arange(2)):
        pass
    summary = tr.finalize()
    assert summary["spans"] == 1
    for name in ("TRACE_np_1.jsonl", "TRACE_np_1.trace.json",
                 "METRICS_np_1.json"):
        assert (tmp_path / name).exists(), name
    rec = json.loads((tmp_path / "TRACE_np_1.jsonl").read_text())
    assert rec["args"]["weird"] == "7"          # default=str form

    # finalize stays idempotent even if a later call races a failure
    assert tr.finalize() is summary


# ---------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------

def test_trace_report_from_metrics_and_jsonl(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import trace_report

    tr = Tracer(mode="trace", directory=str(tmp_path), label="r_3")
    with tr.span("dispatch", "dispatch", sim_t0=0, sim_t1=10):
        time.sleep(0.002)
    time.sleep(0.05)
    tr.record("aot.compile:run", "compile", 0.04)
    tr.finalize()

    m = trace_report.load_metrics(str(tmp_path / "METRICS_r_3.json"))
    trace_report.print_report(m)
    out = capsys.readouterr().out
    assert "dominant phase:" in out and "compile" in out

    m2 = trace_report.load_metrics(str(tmp_path / "TRACE_r_3.jsonl"))
    assert m2["spans"] == 2
    assert m2["phases"]["compile_s"] == pytest.approx(0.04, abs=0.01)
    # jsonl aggregation keeps the sum-to-total contract too
    assert sum(m2["phases"].values()) == pytest.approx(
        m2["total_wall_s"], rel=0.01, abs=0.01)
    trace_report.print_report(m2, top=2)
    out = capsys.readouterr().out
    assert "slowest" in out


# ---------------------------------------------------------------------
# watchdog embedding
# ---------------------------------------------------------------------

def test_watchdog_dump_embeds_recent_spans(tmp_path):
    from shadow_tpu.core.manager import RoundWatchdog, SimStats

    tr = Tracer(mode="summary")
    with tr.span("dispatch", "dispatch", sim_t0=0, sim_t1=7):
        pass

    class StubManager:
        stats = SimStats()
        hosts = []
        tracer = tr

        def dump_state(self):
            return "  host web0: events=3"

    dumps = []
    dump_path = str(tmp_path / "stall.txt")
    wd = RoundWatchdog(StubManager(), 0.15, on_stall=dumps.append,
                       dump_path=dump_path)
    wd.start()
    deadline = time.monotonic() + 10
    while not wd.fired and time.monotonic() < deadline:
        time.sleep(0.02)
    wd.stop()
    assert wd.fired
    assert "host web0" in dumps[0]
    assert "completed span(s)" in dumps[0]
    assert "dispatch" in dumps[0]
    on_disk = open(dump_path).read()
    assert "dispatch" in on_disk


# ---------------------------------------------------------------------
# end-to-end: bit-identity across modes + artifacts from a real run
# ---------------------------------------------------------------------

E2E_YAML = """
general:
  stop_time: 2s
  seed: 3
  data_directory: {data}
experimental:
  scheduler_policy: tpu
  telemetry: {mode}
  telemetry_path: {tel}
hosts:
  server:
    processes:
    - {{path: model:tgen_server, start_time: 100ms}}
  client:
    quantity: 2
    processes:
    - {{path: model:tgen_client, args: server=server size=4KiB
        count=3 pause=100ms, start_time: 200ms}}
"""


def _e2e(tmp_path, mode):
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller

    tel = tmp_path / f"tel_{mode}"
    cfg = load_config_str(E2E_YAML.format(
        mode=mode, tel=tel, data=tmp_path / mode / "shadow.data"))
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok
    return stats, [h.trace_checksum for h in c.sim.hosts], tel


def test_e2e_modes_bit_identical_and_trace_artifacts(tmp_path):
    s_off, chk_off, _ = _e2e(tmp_path, "off")
    s_sum, chk_sum, _ = _e2e(tmp_path, "summary")
    s_tr, chk_tr, tel = _e2e(tmp_path, "trace")
    # the hard contract: tracing never perturbs the simulation
    assert chk_off == chk_sum == chk_tr
    assert s_off.telemetry is None
    assert s_sum.telemetry is not None
    assert set(s_sum.telemetry["phases"]) == {
        f"{p}_s" for p in PHASES}
    # trace artifacts exist and the walls sum to the total
    mfiles = list(tel.glob("METRICS_*.json"))
    tfiles = list(tel.glob("TRACE_*.trace.json"))
    jfiles = list(tel.glob("TRACE_*.jsonl"))
    assert mfiles and tfiles and jfiles
    m = json.loads(mfiles[0].read_text())
    assert sum(m["phases"].values()) == pytest.approx(
        m["total_wall_s"], rel=0.1)
    # the dispatch spans carry sim windows covering the run — split
    # since PR 11 into the asynchronous issue and the blocking sync,
    # which must pair up over identical windows
    recs = [json.loads(ln) for ln in
            jfiles[0].read_text().strip().splitlines()]
    issue = [r for r in recs if r["name"] == "dispatch.issue"]
    sync = [r for r in recs if r["name"] == "dispatch.sync"]
    assert issue and sync and sync[-1]["sim_t1"] == 2 * 10**9
    assert [(r["sim_t0"], r["sim_t1"]) for r in issue] == \
        [(r["sim_t0"], r["sim_t1"]) for r in sync]
    # and SimStats carries the same summary the file holds
    assert s_tr.telemetry["phases"] == m["phases"]


def test_failover_rerun_lands_in_same_trace(tmp_path, monkeypatch):
    """Satellite: the hybrid failover rerun shares its parent's
    flight recorder — its spans land in the SAME trace under a
    `failover` phase, and the METRICS walls still sum to total (the
    host bucket is the residual by construction, so the failover
    span's self-time must not double-count the inner run's spans)."""
    import shadow_tpu.device.engine as eng
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller

    def dead(self, state, stop=None, final_stop=None):
        raise RuntimeError("UNAVAILABLE: device went away")

    monkeypatch.setattr(eng.DeviceEngine, "run", dead)
    tel = tmp_path / "tel_failover"
    cfg = load_config_str(E2E_YAML.format(
        mode="trace", tel=tel, data=tmp_path / "fo" / "shadow.data"))
    cfg.experimental.failover = "hybrid"
    cfg.experimental.dispatch_segment = 500_000_000
    stats = Controller(cfg).run()
    assert stats.ok
    summary = stats.telemetry
    assert summary is not None
    # ONE finalized recorder for the whole incident: the rerun did
    # not write its own METRICS/TRACE set
    mfiles = list(tel.glob("METRICS_*.json"))
    jfiles = list(tel.glob("TRACE_*.jsonl"))
    assert len(mfiles) == 1 and len(jfiles) == 1
    recs = [json.loads(ln) for ln in
            jfiles[0].read_text().strip().splitlines()]
    fo = [r for r in recs if r["phase"] == "failover"]
    assert fo and fo[0]["name"] == "failover.hybrid_rerun"
    # the hybrid rerun's own spans (judge flushes, at minimum) are in
    # the SAME stream, after the device prefix's dispatch spans
    assert any(r["phase"] == "judge" for r in recs)
    assert any(r["name"] == "dispatch.issue" for r in recs)
    # walls still sum to total (host is the residual)
    assert sum(summary["phases"].values()) == pytest.approx(
        summary["total_wall_s"], rel=0.1)
    assert summary["span_counts"].get("failover", 0) >= 1


def test_ensemble_heartbeat_rate_columns(caplog):
    # satellite: per-replica [ensemble-heartbeat] lines carry a
    # pkts/s-since-last-heartbeat rate and cumulative retry/replan
    # counts (stub runner — the line format is the contract)
    from types import SimpleNamespace

    import numpy as np

    from shadow_tpu.ensemble.campaign import EnsembleRunner

    r = SimpleNamespace(
        sim=SimpleNamespace(hosts=[SimpleNamespace(host_id=0),
                                   SimpleNamespace(host_id=1)]),
        worlds=SimpleNamespace(R=2),
        retries=1, replans=2, _hb_mark=None)
    states = {k: np.arange(4).reshape(2, 2)
              for k in ("n_exec", "n_sent", "n_drop", "n_deliv")}
    with caplog.at_level(logging.INFO):
        EnsembleRunner._emit_heartbeats(r, 10**9, states)
        EnsembleRunner._emit_heartbeats(r, 2 * 10**9, states)
    lines = [m for m in caplog.messages
             if "[ensemble-heartbeat]" in m]
    assert len(lines) == 4                   # 2 replicas x 2 beats
    assert "pkts/s=n/a" in lines[0]          # no previous mark
    assert "retries=1" in lines[0] and "replans=2" in lines[0]
    assert "replica=1" in lines[1]
    # the second beat rates against the first (0 new packets -> 0)
    assert "pkts/s=0" in lines[2]


def test_supervise_heartbeat_line(tmp_path, caplog):
    # satellite: the aggregate [supervise-heartbeat] line carries a
    # pkts/s rate and cumulative retry/replan counts
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller

    cfg = load_config_str(E2E_YAML.format(
        mode="summary", tel=tmp_path / "tel",
        data=tmp_path / "hb" / "shadow.data"))
    cfg.general.heartbeat_interval = 5 * 10**8
    with caplog.at_level(logging.INFO):
        stats = Controller(cfg).run()
    assert stats.ok
    lines = [r.getMessage() for r in caplog.records
             if "[supervise-heartbeat]" in r.getMessage()]
    assert lines, "no supervise heartbeat lines"
    assert "pkts/s=n/a" in lines[0]          # no previous mark yet
    for ln in lines:
        assert "retries=0" in ln and "replans=0" in ln
    if len(lines) > 1:
        assert "pkts/s=n/a" not in lines[1]
