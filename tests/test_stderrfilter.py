"""utils/stderrfilter.py: known-noise XLA line filtering — the pure
tail helper and the fd-level pipe filter the bench/graft entry points
install (MULTICHIP_* tail capture satellite)."""

import os

from shadow_tpu.utils import stderrfilter

# the shape of the real offender (MULTICHIP_r05.json): one multi-KB
# line from cpu_aot_loader
NOISE = ("1 14:23:23.702412 8979 cpu_aot_loader.cc:210] Loading "
         "XLA:CPU AOT result. Target machine feature "
         "+prefer-no-gather is not supported on the host machine. "
         "Machine type used for XLA:CPU compilation doesn't match "
         + "+avx512," * 400
         + " This could lead to execution errors such as SIGILL.")


def test_filter_tail_drops_noise_keeps_last_meaningful():
    lines = [f"useful {i}" for i in range(20)]
    text = "\n".join(lines[:5] + [NOISE] + lines[5:] + [NOISE, ""])
    out = stderrfilter.filter_tail(text, keep=10)
    assert "cpu_aot_loader" not in out
    assert out.splitlines() == [f"useful {i}" for i in range(10, 20)]


def test_filter_tail_all_noise_is_empty():
    assert stderrfilter.filter_tail(NOISE + "\n" + NOISE) == ""


def test_is_noise_line():
    assert stderrfilter.is_noise_line(NOISE)
    assert not stderrfilter.is_noise_line(
        "E0000 something actually went wrong")


def test_fd_filter_passes_real_lines_drops_noise(tmp_path):
    path = tmp_path / "captured.log"
    f = open(path, "wb")
    fd = f.fileno()
    filt = stderrfilter._FdFilter(fd)
    os.write(fd, b"dryrun_multichip(8): 10 rounds OK\n")
    os.write(fd, (NOISE + "\n").encode())
    os.write(fd, b"tgen_1000 slice matches on 8 devices OK\n")
    # unterminated trailing chunk must survive the close (crash
    # output has no trailing newline)
    os.write(fd, b"Traceback (most recent call last)")
    filt.close()
    f.close()
    text = path.read_text()
    assert "cpu_aot_loader" not in text
    assert "dryrun_multichip(8): 10 rounds OK" in text
    assert "tgen_1000 slice matches on 8 devices OK" in text
    assert text.endswith("Traceback (most recent call last)")


def test_fd_filter_env_kill_switch(monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_STDERR_FILTER", "0")
    assert stderrfilter.install_fd_filter() is None
