import pytest

from shadow_tpu.config.units import (
    parse_bandwidth_bits,
    parse_size_bytes,
    parse_time_ns,
)


def test_time():
    assert parse_time_ns("10 ms") == 10_000_000
    assert parse_time_ns("50ms") == 50_000_000
    assert parse_time_ns("1 s") == 10**9
    assert parse_time_ns("2 min") == 120 * 10**9
    assert parse_time_ns("1h") == 3600 * 10**9
    assert parse_time_ns("250 us") == 250_000
    assert parse_time_ns("3 ns") == 3
    assert parse_time_ns(10) == 10 * 10**9      # bare number = seconds
    assert parse_time_ns("10") == 10 * 10**9
    assert parse_time_ns(0.5) == 500_000_000


def test_bandwidth():
    assert parse_bandwidth_bits("10 Mbit") == 10_000_000
    assert parse_bandwidth_bits("1 Gbit") == 10**9
    assert parse_bandwidth_bits("100 kbit") == 100_000
    assert parse_bandwidth_bits("10 MB") == 80_000_000
    assert parse_bandwidth_bits(1000) == 1000


def test_size():
    assert parse_size_bytes("16 MiB") == 16 * 2**20
    assert parse_size_bytes("1 KiB") == 1024
    assert parse_size_bytes("2 MB") == 2_000_000
    assert parse_size_bytes("100 B") == 100
    assert parse_size_bytes(42) == 42


def test_errors():
    with pytest.raises(ValueError):
        parse_time_ns("10 parsecs")
    with pytest.raises(ValueError):
        parse_bandwidth_bits("fast")
