"""End-to-end simulations on the serial (oracle) policy."""

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

PHOLD_YAML = """
general:
  stop_time: 5s
  seed: 7
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  scheduler_policy: serial
hosts:
  peer:
    quantity: 10
    processes:
    - path: model:phold
      args: msgload=2 size=64
      start_time: 1s
"""


def test_phold_runs_and_conserves_messages():
    cfg = load_config_str(PHOLD_YAML)
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok
    # 10 hosts x msgload 2 = 20 messages in flight, bounced every 50 ms
    # from t=1s to t=5s: 20 * (4s / 50ms) = 1600 packet events + 10 boots,
    # minus the last in-flight batch still undelivered at stop.
    assert stats.packets_dropped == 0
    assert stats.events_executed > 1000
    # message population is conserved: sends == deliveries + in-flight(20)
    assert stats.packets_sent - stats.packets_delivered == 20


def test_phold_deterministic():
    t1, t2 = [], []
    Controller(load_config_str(PHOLD_YAML), trace=t1).run()
    Controller(load_config_str(PHOLD_YAML), trace=t2).run()
    assert t1 == t2
    assert len(t1) > 1000


def test_phold_seed_changes_trace():
    t1, t2 = [], []
    Controller(load_config_str(PHOLD_YAML), trace=t1).run()
    cfg2 = load_config_str(PHOLD_YAML, overrides=["general.seed=8"])
    Controller(cfg2, trace=t2).run()
    assert t1 != t2


def test_packet_loss_drops():
    yaml = PHOLD_YAML.replace("packet_loss 0.0", "packet_loss 0.2")
    cfg = load_config_str(yaml)
    c = Controller(cfg)
    stats = c.run()
    # with 20% loss and no retransmission the message population decays;
    # some packets must have been dropped
    assert stats.packets_dropped > 0
    assert (stats.packets_sent
            == stats.packets_delivered + stats.packets_dropped
            + (stats.packets_sent - stats.packets_delivered
               - stats.packets_dropped))


TGEN_YAML = """
general:
  stop_time: 30s
  seed: 1
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: serial
hosts:
  server:
    processes:
    - path: model:tgen_server
      start_time: 1s
  client:
    quantity: 3
    processes:
    - path: model:tgen_client
      args: server=server size=100KiB count=2 pause=1s
      start_time: 2s
"""


def test_tgen_transfer_completes():
    cfg = load_config_str(TGEN_YAML)
    c = Controller(cfg)
    stats = c.run()
    clients = [h for h in c.sim.hosts if h.name.startswith("client")]
    assert len(clients) == 3
    for h in clients:
        assert h.app.downloads_done == 2
        assert h.app.bytes_received == 2 * 100 * 1024
    assert stats.packets_dropped == 0


def test_window_advance_counts_rounds():
    cfg = load_config_str(PHOLD_YAML)
    c = Controller(cfg)
    stats = c.run()
    # lookahead = 50 ms self-path latency... self-path = 50ms (self loop).
    # 4s of activity / 50ms windows ~= 80 rounds (plus boot window).
    assert 50 <= stats.rounds <= 130
