"""Campaign server (shadow_tpu/serve/): the durable submission
journal, the scheduler's admit/preempt/recover loop, and the
crash-safety contract — a kill at any instant loses no campaign, and
every resumed run bit-matches an uninterrupted standalone one.

The drills here run the server IN-PROCESS (tick() driven by the
test, ``crash_fn`` raising :class:`ServerCrash` instead of
``os._exit``), so the kill point is deterministic; the real
SIGKILL-a-daemon version of the same drill is the determinism gate's
``--server`` rung in CI.
"""

import json
import os
import time

import pytest

from shadow_tpu.config import load_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.device.chaos import ChaosInjector, events_from_config
from shadow_tpu.serve import Campaign, Journal
from shadow_tpu.serve.server import CampaignServer, ServerCrash, submit

YAML = """
general:
  stop_time: 800ms
  seed: 9
  heartbeat_interval: 200ms
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


@pytest.fixture
def cfg_path(tmp_path):
    p = tmp_path / "run.yaml"
    p.write_text(YAML.format(extra=""))
    return str(p)


def standalone_sig(cfg_path, data_dir):
    cfg = load_config(cfg_path)
    cfg.general.data_directory = str(data_dir)
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok
    return [[h.name, int(h.trace_checksum), int(h.events_executed),
             int(h.packets_sent), int(h.packets_dropped),
             int(h.packets_delivered)] for h in c.sim.hosts]


def drive(srv, timeout_s=240, until=None):
    """Tick the scheduler until idle (or `until` fires)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        busy = srv.tick()
        if until is not None:
            if until():
                return
        elif not busy:
            return
        time.sleep(0.005)
    raise AssertionError("server drive timed out")


def journal_rows(spool):
    with open(os.path.join(spool, "journal.jsonl"),
              encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def result_of(spool, cid):
    with open(os.path.join(spool, "campaigns", cid, "RESULT.json"),
              encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the journal: durable append + last-state-wins replay
# ---------------------------------------------------------------------------

def test_journal_replay_last_state_wins(tmp_path):
    j = Journal(str(tmp_path))
    j.server_event("server_start", restarts=1)
    j.transition("c0000", "QUEUED", config="a.yaml", priority=3,
                 seq=0)
    j.transition("c0001", "QUEUED", config="b.yaml", priority=0,
                 seq=1)
    j.transition("c0000", "RUNNING", attempts=1)
    j.transition("c0000", "PREEMPTED", resume_path="/x/ck.t1",
                 preemptions=1)
    campaigns, meta = j.replay()
    assert meta["server_starts"] == 1 and meta["torn_lines"] == 0
    c0 = campaigns["c0000"]
    assert (c0.state, c0.priority, c0.resume_path, c0.preemptions) \
        == ("PREEMPTED", 3, "/x/ck.t1", 1)
    assert campaigns["c0001"].state == "QUEUED"


def test_journal_rejects_unknown_state(tmp_path):
    with pytest.raises(ValueError, match="unknown campaign state"):
        Journal(str(tmp_path)).transition("c0000", "LIMBO")


def test_journal_tolerates_torn_final_line(tmp_path):
    j = Journal(str(tmp_path))
    j.transition("c0000", "QUEUED", config="a.yaml", seq=0)
    j.transition("c0000", "RUNNING", attempts=1)
    # the crash frontier: a kill mid-append tears the last line
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"cid": "c0000", "state": "DO')
    campaigns, meta = j.replay()
    assert meta["torn_lines"] == 1
    # replay lands on the last DURABLE state, not the torn one
    assert campaigns["c0000"].state == "RUNNING"
    # and appending after a tear starts a fresh, parseable line
    j.transition("c0000", "PREEMPTED", resume_path="")
    campaigns, meta = j.replay()
    assert campaigns["c0000"].state == "PREEMPTED"


def test_replay_fields_round_trip(tmp_path):
    j = Journal(str(tmp_path))
    j.transition("c0000", "QUEUED", config="a.yaml", priority=2,
                 seq=5, overrides=["general.seed=7"], sub="sub_1.json",
                 submitted_wall=123.5)
    c = j.replay()[0]["c0000"]
    assert isinstance(c, Campaign)
    assert (c.config, c.priority, c.seq, c.overrides, c.sub,
            c.submitted_wall) == ("a.yaml", 2, 5,
                                  ["general.seed=7"], "sub_1.json",
                                  123.5)


# ---------------------------------------------------------------------------
# the scheduler: submit -> DONE, namespaced artifacts
# ---------------------------------------------------------------------------

def test_server_completes_campaign_bit_identical(tmp_path, cfg_path):
    ref = standalone_sig(cfg_path, tmp_path / "ref.data")
    spool = str(tmp_path / "spool")
    submit(spool, cfg_path, priority=1)
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    drive(srv)
    srv._shutdown()
    res = result_of(spool, "c0000")
    assert res["state"] == "DONE" and res["attempts"] == 1
    # the robustness claim's baseline: a served run IS a standalone
    # run — same Controller path, same signature
    assert res["signature"] == ref
    states = [r.get("state") or r.get("event")
              for r in journal_rows(spool)]
    assert states == ["server_start", "QUEUED", "ADMITTED",
                      "RUNNING", "DONE", "server_stop"]
    cdir = os.path.join(spool, "campaigns", "c0000")
    # per-campaign namespacing: rotation checkpoints and telemetry
    # records live under the campaign directory
    assert any(n.startswith("ck.npz.t") for n in os.listdir(cdir))
    assert any(n.startswith("METRICS_")
               for n in os.listdir(os.path.join(cdir, "artifacts")))
    # the server SLO summary record
    slo = json.load(open(os.path.join(spool, "SLO_server.json")))
    assert slo["done"] == 1 and slo["failed"] == 0


def test_server_refuses_over_budget_with_readable_diagnostic(
        tmp_path):
    p = tmp_path / "hog.yaml"
    p.write_text(YAML.format(
        extra="  admission: strict\n  device_memory_budget: 4KiB"))
    spool = str(tmp_path / "spool")
    submit(spool, str(p))
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    drive(srv)
    srv._shutdown()
    res = result_of(spool, "c0000")
    assert res["state"] == "REFUSED"
    # the diagnostic must carry the admission story (levers + budget),
    # not a bare traceback tail
    assert "admission" in res["diagnostic"]
    assert "budget" in res["diagnostic"]
    assert srv.slo["refused"] == 1 and srv.slo["failed"] == 0


def test_server_classifies_bad_config_as_failed(tmp_path):
    p = tmp_path / "broken.yaml"
    p.write_text("general:\n  stop_time: sideways\n")
    spool = str(tmp_path / "spool")
    submit(spool, str(p))
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    drive(srv)
    srv._shutdown()
    res = result_of(spool, "c0000")
    assert res["state"] == "FAILED" and res["diagnostic"]


# ---------------------------------------------------------------------------
# crash-safety: kill the server mid-campaign, restart, bit-identical
# ---------------------------------------------------------------------------

def test_server_crash_recovery_resumes_bit_identical(tmp_path,
                                                     cfg_path):
    ref = standalone_sig(cfg_path, tmp_path / "ref.data")
    spool = str(tmp_path / "spool")
    submit(spool, cfg_path)

    def crash():
        raise ServerCrash()

    srv = CampaignServer(spool, poll_s=0.0, crash_fn=crash)
    srv.recover()
    cdir = os.path.join(spool, "campaigns", "c0000")

    def checkpointed():
        # arm the chaos server_crash drill the moment the first
        # rotation checkpoint lands — the next tick kills the server
        if srv.chaos is None and os.path.isdir(cdir) and any(
                n.startswith("ck.npz.t") for n in os.listdir(cdir)):
            srv.chaos = ChaosInjector(events_from_config(
                [{"kind": "server_crash", "tick": 0}]))
        return False

    with pytest.raises(ServerCrash):
        drive(srv, until=checkpointed)
    assert srv.chaos is not None, \
        "the run finished before its first rotation checkpoint"

    # restart: journal replay must requeue c0000 from the newest
    # readable checkpoint and complete it bit-identically
    srv2 = CampaignServer(spool, poll_s=0.0)
    srv2.recover()
    camp = srv2.campaigns["c0000"]
    assert camp.state == "PREEMPTED"
    assert camp.resume_path and os.path.exists(camp.resume_path)
    assert "restart" in camp.diagnostic
    drive(srv2)
    srv2._shutdown()
    res = result_of(spool, "c0000")
    assert res["state"] == "DONE" and res["attempts"] == 2
    assert res["signature"] == ref
    starts = sum(1 for r in journal_rows(spool)
                 if r.get("event") == "server_start")
    assert starts == 2
    assert srv2.slo["requeued_on_restart"] == 1


def test_recover_requeues_running_without_checkpoint_from_scratch(
        tmp_path, cfg_path):
    # the kill outran the first rotation save: no resume artifact
    # exists, so replay must restart the campaign from scratch —
    # losing progress, never the campaign
    spool = str(tmp_path / "spool")
    j = Journal(spool)
    j.server_event("server_start", restarts=1)
    j.transition("c0000", "QUEUED", config=cfg_path, seq=0)
    j.transition("c0000", "ADMITTED")
    j.transition("c0000", "RUNNING", attempts=1)
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    camp = srv.campaigns["c0000"]
    assert camp.state == "PREEMPTED" and camp.resume_path == ""
    assert "scratch" in camp.diagnostic


# ---------------------------------------------------------------------------
# priority: a higher-priority arrival reclaims the slot via the drain
# ---------------------------------------------------------------------------

def test_priority_arrival_preempts_and_resumes_bit_identical(
        tmp_path, cfg_path):
    ref = standalone_sig(cfg_path, tmp_path / "ref.data")
    spool = str(tmp_path / "spool")
    submit(spool, cfg_path, priority=0)
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    state = {"submitted": False}

    def inject_high_priority():
        # submit the urgent campaign once the low-priority one is
        # mid-flight (its runner's guard exists => it is draining-
        # capable); the scheduler must then request the rc-75 drain
        if not state["submitted"] and srv._slot is not None:
            runner = srv._runner_of(srv._slot)
            if runner is not None and getattr(runner, "guard",
                                              None) is not None:
                submit(spool, cfg_path, priority=5)
                state["submitted"] = True
        return state["submitted"]

    drive(srv, until=inject_high_priority)
    drive(srv)   # then run the queue dry
    srv._shutdown()
    lo, hi = result_of(spool, "c0000"), result_of(spool, "c0001")
    assert lo["state"] == "DONE" and hi["state"] == "DONE"
    assert lo["preemptions"] == 1 and lo["attempts"] == 2
    # the urgent campaign finished FIRST, and neither signature moved
    seq = [(r.get("cid"), r.get("state")) for r in journal_rows(spool)
           if r.get("state")]
    dones = [cid for cid, s in seq if s == "DONE"]
    assert dones == ["c0001", "c0000"]
    assert ("c0000", "PREEMPTED") in seq
    assert lo["signature"] == ref and hi["signature"] == ref
