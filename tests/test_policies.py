"""Cross-policy equivalence: every scheduler policy must produce the
same per-host observable schedule as the serial oracle (the reference's
determinism guarantee, independent of worker count — SURVEY §2.7)."""

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

YAML = """
general:
  stop_time: 3s
  seed: 11
  parallelism: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "25 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "10 ms" packet_loss 0.05 ]
        edge [ source 1 target 1 latency "25 ms" packet_loss 0.0 ]
      ]
experimental:
  scheduler_policy: serial
hosts:
  left:
    quantity: 4
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload=2
      start_time: 100ms
  right:
    quantity: 4
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload=2
      start_time: 100ms
"""


def _run(policy: str):
    trace = []
    cfg = load_config_str(
        YAML, overrides=[f"experimental.scheduler_policy={policy}"])
    c = Controller(cfg, trace=trace)
    stats = c.run()
    return stats, trace


def _per_host(trace):
    out = {}
    for t, dst, src, kind in trace:
        out.setdefault(dst, []).append((t, src, kind))
    return out


@pytest.mark.parametrize("policy", ["host", "steal", "thread",
                                    "threadXthread", "threadXhost"])
def test_policy_matches_serial_oracle(policy):
    s_stats, s_trace = _run("serial")
    p_stats, p_trace = _run(policy)
    assert s_stats.events_executed == p_stats.events_executed
    assert s_stats.packets_sent == p_stats.packets_sent
    assert s_stats.packets_dropped == p_stats.packets_dropped
    assert s_stats.packets_delivered == p_stats.packets_delivered
    # identical per-host schedules (global interleaving may differ)
    assert _per_host(s_trace) == _per_host(p_trace)
    assert s_stats.events_executed > 200


@pytest.mark.parametrize("policy", ["thread", "threadXthread", "host"])
def test_lp_multiplexing_matches_oracle(policy):
    """More worker contexts than LPs: the LogicalProcessors layer
    (logical_processor.rs analogue) multiplexes 6 workers onto 2 OS
    threads with stealing — same per-host schedule as serial."""
    s_stats, s_trace = _run("serial")
    cfg = load_config_str(YAML, overrides=[
        f"experimental.scheduler_policy={policy}",
        "experimental.workers=6",
        "general.parallelism=2",
    ])
    trace = []
    c = Controller(cfg, trace=trace)
    p_stats = c.run()
    assert c.manager.policy.n_workers == 6
    assert c.manager.policy.parallelism == 2
    assert s_stats.events_executed == p_stats.events_executed
    assert s_stats.packets_sent == p_stats.packets_sent
    assert _per_host(s_trace) == _per_host(trace)


def test_affinity_assignment_shapes():
    """Affinity module (affinity.c analogue): every worker gets a CPU
    from the allowed set, spreading before reuse."""
    import os

    from shadow_tpu.utils import affinity

    cpus = affinity.platform_cpus()
    allowed = os.sched_getaffinity(0)
    assert cpus and set(cpus) <= allowed
    assert len(set(cpus)) == len(cpus)          # no duplicates
    a = affinity.good_worker_affinity(len(cpus) * 2 + 1)
    assert len(a) == len(cpus) * 2 + 1
    assert set(a) <= allowed
    # pinning the current thread is either applied or soft-refused
    assert affinity.pin_current_thread(cpus[0]) in (True, False)
    os.sched_setaffinity(0, allowed)            # restore for the suite
