"""File-syscall emulation (the special-path slice of ref file.c /
fileat.c): deterministic RNG devices, the simulated /etc/hosts, and
per-host relative-path isolation — under BOTH interposition backends.
"""

import os

import pytest

from test_managed import (  # noqa: F401  (fixture re-export)
    base_cfg,
    plugins,
    read_stdout,
    run_sim,
)


def _cfg(data: str, method: str) -> str:
    return base_cfg(data).replace(
        "hosts:\n",
        f"experimental:\n  interpose_method: {method}\nhosts:\n")


METHODS = ["preload", "ptrace"]


@pytest.mark.parametrize("method", METHODS)
def test_urandom_deterministic(plugins, tmp_path, method):
    """open/read/pread of /dev/urandom and /dev/random serve the
    host's seeded stream: bit-identical across runs, chardev fstat."""
    outs = []
    for run in range(2):
        data = str(tmp_path / f"{method}{run}" / "shadow.data")
        cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['urandom_check']}
      start_time: 1s
"""
        stats, _ = run_sim(cfg, tmp_path / f"{method}{run}")
        assert stats.ok
        out = read_stdout(data, "alice", "urandom_check")
        assert "done" in out
        lines = out.splitlines()
        assert lines[0].startswith("r1 ") and len(lines[0]) == 35
        assert lines[2] == "chardev 1"
        outs.append(out)
    assert outs[0] == outs[1]


@pytest.mark.parametrize("method", METHODS)
def test_relative_path_isolation(plugins, tmp_path, method):
    """The same relative path ("state.txt") on two hosts lands in each
    host's own data dir; /etc/hosts reads the SIMULATED name map."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['file_iso_check']}
      args: from-alice
      start_time: 1s
  bob:
    network_node_id: 1
    processes:
    - path: {plugins['file_iso_check']}
      args: from-bob
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out_a = read_stdout(data, "alice", "file_iso_check")
    out_b = read_stdout(data, "bob", "file_iso_check")
    assert "state from-alice" in out_a
    assert "state from-bob" in out_b
    # the files physically live in separate host dirs
    assert open(os.path.join(data, "hosts", "alice",
                             "state.txt")).read() == "from-alice"
    assert open(os.path.join(data, "hosts", "bob",
                             "state.txt")).read() == "from-bob"
    # simulated hosts file: localhost + alice + bob = 3 lines
    assert "hosts_lines 3" in out_a
    assert "hosts_lines 3" in out_b
    # path-stat agrees with the served content; writes are refused
    assert "stat_coherent 1" in out_a
    assert "hosts_readonly 1" in out_a


def test_getaddrinfo_under_ptrace(plugins, tmp_path):
    """Name resolution under ptrace has no shim override: libc reads
    /etc/hosts & friends raw, so the emulated files must steer it to
    the simulated map (resolver_check connects BY NAME to prove it)."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, "ptrace") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['tcp_server']}
      args: 9000
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['resolver_check']}
      args: server 9000
      start_time: 2s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "client", "resolver_check")
    assert "hostname client" in out
    assert "resolved server 11.0.0.1:9000" in out
    assert stats.ok


@pytest.mark.parametrize("method", METHODS)
def test_deterministic_rusage_topology(plugins, tmp_path, method):
    """getrusage/times report SIMULATED time; the scheduler sees one
    CPU; getcpu pins to 0 — real-machine resource/topology state
    cannot leak into plugin decisions."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['rusage_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "rusage_check")
    lines = out.splitlines()
    # start 1s + 250ms sleep = sim t 1.25s
    assert lines[0] == "utime 1.250000 stime 0"
    assert lines[1] == "ticks 125 utime_t 125"
    assert lines[2] == "ncpu 1 cpu0 1"
    assert lines[3] == "nproc_conf 1"
    assert lines[4] == "getcpu 0 0"
    assert lines[5] == "done"
    assert stats.ok


@pytest.mark.parametrize("method", METHODS)
def test_fileat_family(plugins, tmp_path, method):
    """The fd-mediated file family (ref file.c/fileat.c): dirfd-
    relative openat/mkdirat/renameat/unlinkat/linkat/symlinkat/
    readlinkat/faccessat, ftruncate/fsync/fchmod/flock/pread/pwrite,
    sorted deterministic getdents, and '..' confinement."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['fileat_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "fileat_check")
    assert "done" in out, out
    for line in out.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[1] in ("0", "1") \
                and parts[0] != "dirents":
            assert parts[1] == "1", f"{line!r} failed:\n{out}"
    # getdents serves a SORTED snapshot ('.', '..', then names):
    # deterministic across runs and filesystems
    assert "dirents .,..,a.txt,hard2,ln," in out
    # the confined ops physically landed inside alice's host dir
    sub = os.path.join(data, "hosts", "alice", "sub")
    assert os.path.isdir(sub)
    assert open(os.path.join(sub, "a.txt")).read() == "hello"
    # ... and the escape attempts did NOT create files outside it
    assert not os.path.exists(os.path.join(data, "escape.txt"))
    assert not os.path.exists(
        os.path.join(data, "hosts", "escape.txt"))


@pytest.mark.parametrize("method", METHODS)
def test_fileat_two_host_isolation(plugins, tmp_path, method):
    """dirfd-relative ops on two hosts stay inside each host's own
    data dir (the isolation test extended to the at-family)."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['fileat_check']}
      start_time: 1s
  bob:
    network_node_id: 1
    processes:
    - path: {plugins['fileat_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    for host in ("alice", "bob"):
        out = read_stdout(data, host, "fileat_check")
        assert "done" in out, out
        f = os.path.join(data, "hosts", host, "sub", "a.txt")
        assert open(f).read() == "hello"


@pytest.mark.parametrize("method", METHODS)
def test_limits_prctl(plugins, tmp_path, method):
    """prlimit64/getrlimit report DETERMINISTIC limits (never the real
    machine's), set-then-get round-trips, and PR_SET_NAME/PDEATHSIG
    are virtualized."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['limits_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "limits_check")
    lines = out.splitlines()
    assert lines[0] == "nofile 1024 1048576"
    assert lines[1] == "setrlimit 0"
    assert lines[2] == "nofile2 512 1048576"
    assert lines[3] == "stack_soft 8388608"
    assert lines[4] == "pdeathsig 15"
    assert lines[5] == "name worker0"
    assert lines[6] == "done"


@pytest.mark.parametrize("method", METHODS)
def test_mmap_of_emulated_file(plugins, tmp_path, method):
    """mmap of a data-dir file (an emulated fd): realized through the
    simulator's /proc fd under ptrace (ref mman.c:72-126 procfs
    technique) with MAP_SHARED write-through visible to pread on the
    same fd; refused with ENODEV under preload, where the read()
    fallback must see identical bytes."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['mmap_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    out = read_stdout(data, "alice", "mmap_check")
    assert "done" in out, out
    if method == "ptrace":
        assert "mmap_errno 0" in out
        assert "map_read 1" in out
        assert "write_through 1" in out
    else:
        assert "mmap_errno 19" in out       # ENODEV
        assert "fallback_read 1" in out
    # the mapped writes landed in the real per-host file
    f = os.path.join(data, "hosts", "alice", "mapme.bin")
    content = open(f, "rb").read()
    if method == "ptrace":
        assert content[8:16] == b"WRITTEN!"
    else:
        assert content[:8] == b"01234567"


@pytest.mark.parametrize("method", METHODS)
def test_posix_record_locks(plugins, tmp_path, method):
    """fcntl record locks across two processes on one host: conflicts
    report EAGAIN, F_GETLK names the holder's VIRTUAL pid, disjoint
    ranges and same-process re-locks succeed, locks die with their
    owner; fstatfs reports the deterministic filesystem."""
    data = str(tmp_path / "shadow.data")
    cfg = _cfg(data, method) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['lock_check']}
      args: hold
      start_time: 1s
    - path: {plugins['lock_check']}
      args: probe
      start_time: 1100ms
"""
    stats, _ = run_sim(cfg, tmp_path)
    assert stats.ok
    d = os.path.join(data, "hosts", "alice")
    outs = {}
    for f in sorted(os.listdir(d)):
        if f.endswith(".stdout"):
            outs[f] = open(os.path.join(d, f)).read()
    hold = next(v for v in outs.values() if "held" in v)
    probe = next(v for v in outs.values() if "conflict" in v)
    hold_pid = int(hold.split("pid=")[1].split()[0])
    assert hold_pid >= 1000                  # virtual pid space
    assert "conflict 1" in probe
    assert f"getlk type=1 pid={hold_pid}" in probe
    assert "disjoint 1" in probe
    assert "same_process 1" in probe
    # OFD: description-owned — the same process's second description
    # conflicts and GETLK reports pid -1
    assert "ofd_first 1" in probe
    assert "ofd_conflict 1" in probe
    assert "ofd_getlk type=1 pid=-1" in probe
    assert "fstatfs type=ef53 bsize=4096 namelen=255" in probe
    assert "freed 1" in probe
    assert "done" in probe
