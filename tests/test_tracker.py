"""Direct unit tests for host/tracker.py heartbeat CSV lines.

The `[shadow-heartbeat]` surface is parsed by existing shadow
log-parsing workflows (docs/migrating_from_shadow.md), so its shape
is a compatibility contract: the header row is emitted exactly once,
the node/socket column counts stay stable and match their headers,
and socket lines cover exactly the host's live TCP connections.
Until now only the end-to-end device tests exercised it.
"""

import logging

import pytest

from shadow_tpu.host.tracker import Tracker


class FakeEth:
    def __init__(self):
        self.bytes_sent = 0
        self.bytes_received = 0


class FakeSock:
    class _State:
        name = "ESTABLISHED"

    def __init__(self, sent=3, retrans=1, received=42):
        self.state = self._State()
        self.segments_sent = sent
        self.segments_retransmitted = retrans
        self.bytes_received = received


class FakeNet:
    def __init__(self, conns=None):
        self.eth = FakeEth()
        self._conns = conns or {}


class FakeHost:
    def __init__(self, net=None):
        self.packets_sent = 0
        self.packets_dropped = 0
        self.net = net
        self.apps = ()


def hb_lines(caplog, tag):
    return [r.getMessage() for r in caplog.records
            if f"[{tag}]" in r.getMessage()]


@pytest.fixture
def tracker_host(caplog):
    caplog.set_level(logging.INFO, logger="shadow_tpu.heartbeat")
    return Tracker("web0", 10**9), FakeHost()


def test_node_header_emitted_once(tracker_host, caplog):
    tr, host = tracker_host
    tr.heartbeat(10**9, host)
    tr.heartbeat(2 * 10**9, host)
    tr.heartbeat(3 * 10**9, host)
    assert len(hb_lines(caplog, "node-header")) == 1
    assert len(hb_lines(caplog, "node")) == 3


def test_node_column_count_matches_header(tracker_host, caplog):
    tr, host = tracker_host
    host.packets_sent = 7
    host.packets_dropped = 2
    tr.on_event()
    tr.on_event()
    tr.heartbeat(10**9, host)
    header = hb_lines(caplog, "node-header")[0]
    row = hb_lines(caplog, "node")[0]
    cols = header.split("[node-header] ")[1].split(",")
    vals = row.split("[node] ")[1].split(",")
    assert len(vals) == len(cols) == 9
    # time,name,events,packets-sent,packets-dropped,...
    assert vals[0] == "1"
    assert vals[1] == "web0"
    assert vals[2] == "2"        # on_event x2 this interval
    assert vals[3] == "7"
    assert vals[4] == "2"


def test_node_counters_are_interval_deltas(tracker_host, caplog):
    tr, host = tracker_host
    host.packets_sent = 5
    tr.heartbeat(10**9, host)
    host.packets_sent = 8        # +3 since the last beat
    tr.heartbeat(2 * 10**9, host)
    rows = [ln.split("[node] ")[1].split(",")
            for ln in hb_lines(caplog, "node")]
    assert rows[0][3] == "5"
    assert rows[1][3] == "3"


def test_set_events_total_diffs_cumulative(tracker_host, caplog):
    # device path: the engine reports CUMULATIVE per-host event
    # counts; the tracker diffs them into interval values
    tr, host = tracker_host
    tr.set_events_total(10)
    tr.heartbeat(10**9, host)
    tr.set_events_total(25)
    tr.heartbeat(2 * 10**9, host)
    rows = [ln.split("[node] ")[1].split(",")
            for ln in hb_lines(caplog, "node")]
    assert rows[0][2] == "10"
    assert rows[1][2] == "15"


def test_socket_lines_match_open_sockets(caplog):
    caplog.set_level(logging.INFO, logger="shadow_tpu.heartbeat")
    conns = {(8080, 3, 50000): FakeSock(sent=5, retrans=0,
                                        received=100),
             (8081, 4, 50001): FakeSock(sent=9, retrans=2,
                                        received=7)}
    host = FakeHost(net=FakeNet(conns))
    tr = Tracker("srv", 10**9)
    tr.heartbeat(10**9, host)
    headers = hb_lines(caplog, "socket-header")
    rows = hb_lines(caplog, "socket")
    assert len(headers) == 1
    assert len(rows) == len(conns)
    n_cols = len(headers[0].split("[socket-header] ")[1].split(","))
    for row in rows:
        vals = row.split("[socket] ")[1].split(",")
        assert len(vals) == n_cols == 9
    # sorted by (local-port, peer, peer-port): 8080 first
    first = rows[0].split("[socket] ")[1].split(",")
    assert first[2] == "8080" and first[5] == "ESTABLISHED"
    assert first[6] == "5" and first[7] == "0" and first[8] == "100"
    # a second beat emits no second socket header
    tr.heartbeat(2 * 10**9, host)
    assert len(hb_lines(caplog, "socket-header")) == 1


def test_no_socket_lines_without_connections(caplog):
    caplog.set_level(logging.INFO, logger="shadow_tpu.heartbeat")
    tr = Tracker("lonely", 10**9)
    tr.heartbeat(10**9, FakeHost(net=FakeNet()))
    assert not hb_lines(caplog, "socket-header")
    assert not hb_lines(caplog, "socket")
