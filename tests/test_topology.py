import numpy as np
import pytest

from shadow_tpu import simtime
from shadow_tpu.topology import Topology, parse_gml
from shadow_tpu.topology.attach import Attacher
from shadow_tpu.topology.gml import GmlError
from shadow_tpu.utils.rng import SeededRandom

MS = simtime.SIMTIME_ONE_MILLISECOND

# A 4-vertex line + shortcut:  0 --10ms-- 1 --10ms-- 2 --10ms-- 3
# plus a direct 0--3 edge at 50ms (shortest 0->3 is 30ms via the line).
LINE_GML = """
graph [
  directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "10 Mbit"
         ip_address "10.0.0.1" country_code "US" ]
  node [ id 1 bandwidth_down "200 Mbit" bandwidth_up "20 Mbit"
         ip_address "10.0.1.1" country_code "US" ]
  node [ id 2 bandwidth_down "300 Mbit" bandwidth_up "30 Mbit"
         ip_address "10.1.0.1" country_code "DE" ]
  node [ id 3 bandwidth_down "400 Mbit" bandwidth_up "40 Mbit"
         ip_address "10.1.1.1" country_code "DE" city_code "BER" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.1 ]
  edge [ source 1 target 2 latency "10 ms" packet_loss 0.1 ]
  edge [ source 2 target 3 latency "10 ms" packet_loss 0.1 ]
  edge [ source 0 target 3 latency "50 ms" packet_loss 0.0 ]
]
"""


def test_gml_parse_basic():
    g = parse_gml(LINE_GML)
    assert not g.directed
    assert len(g.nodes) == 4
    assert len(g.edges) == 4
    assert g.nodes[1].get("ip_address") == "10.0.1.1"
    assert g.edges[0].get("latency") == "10 ms"
    assert g.edges[0].get("packet_loss") == 0.1


def test_gml_errors():
    with pytest.raises(GmlError):
        parse_gml("graph [ node [ ] ]")              # missing id
    with pytest.raises(GmlError):
        parse_gml("graph [ edge [ source 0 ] ]")     # missing target
    with pytest.raises(GmlError):
        parse_gml("nothing here")
    with pytest.raises(GmlError):
        parse_gml("graph [ node [ id 0 ]")           # unbalanced


def test_builtin_switch():
    top = Topology.builtin_1_gbit_switch()
    assert top.n_vertices == 1
    assert top.bw_down_bits[0] == 10**9
    # self path = self-loop edge latency (1 ms), reliability 1.0
    assert top.get_latency_ns(0, 0) == 1 * MS
    assert top.get_reliability(0, 0) == 1.0
    assert top.min_latency_ns == 1 * MS


def test_shortest_paths():
    top = Topology.from_gml(LINE_GML)
    # direct neighbors
    assert top.get_latency_ns(0, 1) == 10 * MS
    # 0 -> 2 via 1: 20 ms, reliability 0.9^2
    assert top.get_latency_ns(0, 2) == 20 * MS
    assert abs(top.get_reliability(0, 2) - 0.81) < 1e-6
    # 0 -> 3: line (30ms, 0.9^3) beats direct edge (50ms)
    assert top.get_latency_ns(0, 3) == 30 * MS
    assert abs(top.get_reliability(0, 3) - 0.729) < 1e-6
    # symmetric (undirected)
    np.testing.assert_array_equal(top.latency_ns, top.latency_ns.T)
    # self path: vertex 0's cheapest incident edge (10ms) doubled
    assert top.get_latency_ns(0, 0) == 20 * MS
    assert abs(top.get_reliability(0, 0) - 0.81) < 1e-6
    assert top.min_latency_ns == 10 * MS


def test_direct_mode_requires_complete():
    with pytest.raises(GmlError):
        Topology.from_gml(LINE_GML, use_shortest_path=False)


def test_direct_mode_complete_graph():
    gml = """
    graph [ directed 0
      node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      edge [ source 0 target 1 latency "5 ms" packet_loss 0.0 ]
      edge [ source 0 target 0 latency "2 ms" packet_loss 0.0 ]
      edge [ source 1 target 1 latency "3 ms" packet_loss 0.0 ]
    ]
    """
    top = Topology.from_gml(gml, use_shortest_path=False)
    assert top.complete
    assert top.get_latency_ns(0, 1) == 5 * MS
    assert top.get_latency_ns(0, 0) == 2 * MS   # self loop as-is
    assert top.get_latency_ns(1, 1) == 3 * MS


def test_disconnected_rejected():
    gml = """
    graph [ directed 0
      node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      edge [ source 0 target 0 latency "1 ms" packet_loss 0.0 ]
      edge [ source 1 target 1 latency "1 ms" packet_loss 0.0 ]
    ]
    """
    with pytest.raises(GmlError):
        Topology.from_gml(gml)


def test_validation_errors():
    with pytest.raises(GmlError):  # missing bandwidth
        Topology.from_gml("""graph [ node [ id 0 ]
          edge [ source 0 target 0 latency "1 ms" packet_loss 0.0 ] ]""")
    with pytest.raises(GmlError):  # loss out of range
        Topology.from_gml("""graph [
          node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
          edge [ source 0 target 0 latency "1 ms" packet_loss 1.5 ] ]""")
    with pytest.raises(GmlError):  # zero latency edge
        Topology.from_gml("""graph [
          node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
          edge [ source 0 target 0 latency "0 ms" packet_loss 0.0 ] ]""")


def test_submillisecond_latency_not_clamped():
    gml = """graph [ directed 0
      node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
      edge [ source 0 target 1 latency "100 us" packet_loss 0.0 ]
    ]"""
    top = Topology.from_gml(gml)
    assert top.get_latency_ns(0, 1) == 100_000       # not inflated to 1 ms
    assert top.min_latency_ns == 100_000


def test_attachment():
    top = Topology.from_gml(LINE_GML)
    att = Attacher(top, SeededRandom(1))
    # explicit pin
    a = att.attach(network_node_id=2)
    assert a.vertex == 2
    assert a.bw_down_bits == 300_000_000   # vertex default
    assert a.bw_up_bits == 30_000_000
    # bandwidth override beats vertex default
    a = att.attach(network_node_id=2, bw_down_override=5)
    assert a.bw_down_bits == 5
    # longest-prefix ip match: 10.1.1.7 -> vertex 3 (10.1.1.1)
    a = att.attach(ip_hint="10.1.1.7")
    assert a.vertex == 3
    # country filter: DE -> vertex 2 or 3; city BER -> 3
    a = att.attach(country_hint="DE", city_hint="BER")
    assert a.vertex == 3
    # hint-less attach is deterministic given the seed
    att2 = Attacher(top, SeededRandom(1))
    seq1 = [att.attach().vertex for _ in range(5)]
    # fresh attacher replays only if RNG state matches call-for-call
    att3 = Attacher(top, SeededRandom(1))
    for _ in range(4):
        att3.attach(network_node_id=0)  # pins don't consume RNG draws
    assert att2.attach().vertex == seq1[0]


def test_large_random_graph_paths_match_floyd():
    # cross-check scipy dijkstra path against the scipy-free fallback
    rng = np.random.default_rng(0)
    V = 12
    lines = ["graph [", "  directed 0"]
    for v in range(V):
        lines.append(f'  node [ id {v} bandwidth_down "1 Gbit" '
                     f'bandwidth_up "1 Gbit" ]')
    for a in range(V):
        for b in range(a + 1, V):
            if rng.random() < 0.4 or b == a + 1:
                lat = int(rng.integers(1, 40))
                lines.append(f'  edge [ source {a} target {b} '
                             f'latency "{lat} ms" packet_loss 0.01 ]')
    lines.append("]")
    gml = "\n".join(lines)
    top = Topology.from_gml(gml)
    from shadow_tpu.topology.graph import _all_pairs_minplus
    direct_lat, direct_rel = top._adjacency()
    fb_lat, fb_rel = _all_pairs_minplus(direct_lat, direct_rel, None)
    off = ~np.eye(V, dtype=bool)
    np.testing.assert_array_equal(top.latency_ns[off], fb_lat[off])
    np.testing.assert_allclose(top.reliability[off], fb_rel[off], rtol=1e-5)
