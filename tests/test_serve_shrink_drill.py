"""ROADMAP item (e) drill: a chaos-scripted 4 -> 3 mesh shrink
mid-campaign UNDER THE CAMPAIGN SERVER, with the flight-recorder
before/after comparison (``trace_report --compare``).

Two campaigns through one server against the same workload: a clean
4-shard baseline and a run whose scripted ``device_loss`` forces the
elastic shrink to 3 shards mid-flight. The robustness bar: both
reach DONE with IDENTICAL signatures (device loss costs throughput,
never determinism), and the compare table attributes the shrink
run's extra wall to the failover/reshard phases. The committed
``artifacts/COMPARE_r17_shrink.txt`` is this drill's output
(regenerate with SHADOW_TPU_WRITE_COMPARE=1).
"""

import json
import os
import subprocess
import sys
import time

import pytest

SHRINK_EXTRA = """  failover: shrink
  chaos:
  - {kind: device_loss, segment: 1, shard: 1}
"""

# baseline and shrink differ ONLY in the failover/chaos lines, so the
# compare table isolates what the device loss cost
YAML = """
general:
  stop_time: 800ms
  seed: 9
  heartbeat_interval: 200ms
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
  mesh_shards: 4
  dispatch_segment: 100ms
  state_audit: true
  dispatch_retries: 1
  dispatch_retry_backoff: 0.0
{extra}hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


@pytest.mark.slow
def test_shrink_under_server_bit_identical_with_compare(tmp_path):
    from shadow_tpu.serve.server import CampaignServer, submit

    baseline = tmp_path / "baseline.yaml"
    baseline.write_text(YAML.format(extra=""))
    shrink = tmp_path / "shrink.yaml"
    shrink.write_text(YAML.format(extra=SHRINK_EXTRA))

    spool = str(tmp_path / "spool")
    submit(spool, str(baseline))
    submit(spool, str(shrink))
    srv = CampaignServer(spool, poll_s=0.0)
    srv.recover()
    deadline = time.monotonic() + 480
    while srv.tick() and time.monotonic() < deadline:
        time.sleep(0.005)
    srv._shutdown()

    res = {}
    for cid in ("c0000", "c0001"):
        with open(os.path.join(spool, "campaigns", cid,
                               "RESULT.json"), encoding="utf-8") as f:
            res[cid] = json.load(f)
        assert res[cid]["state"] == "DONE", res[cid]
    # device loss costs wall, never the answer
    assert res["c0000"]["signature"] == res["c0001"]["signature"]

    def metrics_of(cid):
        adir = os.path.join(spool, "campaigns", cid, "artifacts")
        names = [n for n in os.listdir(adir)
                 if n.startswith("METRICS_")]
        assert len(names) == 1, names
        return os.path.join(adir, names[0])

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "trace_report.py"),
         "--compare", metrics_of("c0000"), metrics_of("c0001")],
        capture_output=True, text=True, cwd=repo)
    assert out.returncode == 0, out.stderr
    table = out.stdout
    assert "flight-recorder comparison" in table
    # the shrink run's story must be visible in the attribution:
    # reshard/failover walls exist only on the B (shrink) side
    assert "reshard" in table or "failover" in table
    if os.environ.get("SHADOW_TPU_WRITE_COMPARE"):
        dst = os.path.join(repo, "artifacts",
                           "COMPARE_r17_shrink.txt")
        with open(dst, "w", encoding="utf-8") as f:
            f.write("4-shard baseline vs chaos device_loss 4->3 "
                    "shrink, both under the campaign server\n"
                    "(tests/test_serve_shrink_drill.py; signatures "
                    "bit-identical)\n\n")
            f.write(table)
