"""Preflight resource admission + the OOM degradation ladder
(device/capacity.py footprint/admission_verdict +
device/supervise.py recover_oom + ensemble replica batches).

The contract under test: a run must never OOM blind. Before any
compile, both runners estimate the per-device byte footprint and
compare it to the budget — `admission: strict` refuses over-budget
configs with a readable diagnostic, `auto` statically degrades
(pipeline_depth, then ensemble replica batches) or admits loudly.
At runtime, a deterministic RESOURCE_EXHAUSTED walks a degradation
ladder (halve pipeline depth -> split the ensemble into sequential
replica batches -> halve the dispatch segment -> failover) instead
of draining dispatch_retries, and every rung is bit-identical to
the undegraded run. The footprint model itself is kept honest
against live device bytes within capacity.FOOTPRINT_TOLERANCE.
"""

import gc
import json
import logging

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import capacity
from shadow_tpu.device.runner import DeviceRunner
from shadow_tpu.ensemble.campaign import EnsembleRunner

YAML = """
general:
  stop_time: 800ms
  seed: 9
  heartbeat_interval: 200ms
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""

ENS = """
ensemble:
  replicas: 2
  vary:
    seed: [9, 11]
  record_path: {rec}
"""

# every OOM-ladder run segments so rungs have boundaries to engage at
OOM_BASE = ("  dispatch_segment: 200ms\n"
            "  state_audit: true\n"
            "  dispatch_retries: 1\n"
            "  dispatch_retry_backoff: 0.0\n")


def _run(extra=""):
    c = Controller(load_config_str(YAML.format(extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


@pytest.fixture(scope="module")
def ref():
    """The undegraded reference: signature + stats + controller (its
    engine feeds the footprint computations below)."""
    stats, c = _run("  dispatch_segment: 200ms\n  state_audit: true")
    assert stats.ok
    return _sig(stats, c), stats, c


@pytest.fixture(scope="module")
def ens_full(tmp_path_factory):
    """The full-vmap 2-replica campaign every batched/degraded
    campaign must bit-match."""
    rec = tmp_path_factory.mktemp("ens_full") / "ENSEMBLE.json"
    c = Controller(load_config_str(
        YAML.format(extra="  dispatch_segment: 200ms")
        + ENS.format(rec=rec)))
    stats = c.run()
    assert stats.ok
    f = c.runner.final_state
    return {k: np.asarray(f[k])
            for k in ("chk", "n_exec", "n_sent", "n_drop", "n_deliv")}


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("extra,match", [
    ("  admission: sideways", "admission"),
    ("  device_memory_budget: -4", "device_memory_budget"),
])
def test_schema_rejects_bad_admission_knobs(extra, match):
    with pytest.raises(ValueError, match=match):
        load_config_str(YAML.format(extra=extra))


def test_schema_rejects_admission_knobs_on_cpu_policies():
    serial = YAML.replace("scheduler_policy: tpu",
                          "scheduler_policy: serial")
    for extra, match in (
            ("  admission: strict", "scheduler_policy"),
            ("  device_memory_budget: 1GiB", "device_memory_budget")):
        with pytest.raises(ValueError, match=match):
            load_config_str(serial.format(extra=extra))


def test_schema_parses_budget_sizes_and_admission_choices():
    cfg = load_config_str(YAML.format(
        extra="  device_memory_budget: 8GiB\n  admission: off"))
    assert cfg.experimental.device_memory_budget == 8 * (1 << 30)
    assert cfg.experimental.admission == "off"
    # default: auto, no budget
    cfg = load_config_str(YAML.format(extra=""))
    assert cfg.experimental.admission == "auto"
    assert cfg.experimental.device_memory_budget == 0


def test_schema_bounds_replica_batch(tmp_path):
    ens = ENS.format(rec=tmp_path / "ENSEMBLE.json")
    for batch in (-1, 3):     # campaign has 2 replicas
        with pytest.raises(ValueError, match="replica_batch"):
            load_config_str(
                YAML.format(extra="")
                + ens + f"  replica_batch: {batch}\n")
    cfg = load_config_str(YAML.format(extra="")
                          + ens + "  replica_batch: 1\n")
    assert cfg.ensemble.replica_batch == 1


def test_schema_replica_batch_checkpoint_contract(tmp_path):
    ens = ENS.format(rec=tmp_path / "ENSEMBLE.json")
    # per-batch rotation checkpoints (<save>.b<k>.t<ns>) made batched
    # campaigns preemptible, so save + every is now a valid combo
    cfg = load_config_str(
        YAML.format(
            extra=f"  checkpoint_save: {tmp_path / 'ck.npz'}\n"
                  "  checkpoint_every: 200ms")
        + ens + "  replica_batch: 1\n")
    assert cfg.ensemble.replica_batch == 1
    # but a batched campaign still has no single pause point, so the
    # one-shot save-at-time form stays rejected
    with pytest.raises(ValueError, match="replica_batch"):
        load_config_str(
            YAML.format(
                extra=f"  checkpoint_save: {tmp_path / 'ck.npz'}\n"
                      "  checkpoint_save_time: 200ms")
            + ens + "  replica_batch: 1\n")
    # and save without a rotation cadence can never write anything
    with pytest.raises(ValueError, match="checkpoint_every"):
        load_config_str(
            YAML.format(
                extra=f"  checkpoint_save: {tmp_path / 'ck.npz'}")
            + ens + "  replica_batch: 1\n")


# ---------------------------------------------------------------------------
# preflight admission: strict refusal / auto verdicts
# ---------------------------------------------------------------------------

def test_strict_refusal_is_readable_and_precedes_compile(tmp_path):
    # a private cold AOT cache: if anything compiled before the
    # refusal, an entry would land here
    aot = tmp_path / "aot"
    with pytest.raises(ValueError, match=r"admission: needs .* per "
                                         r"device, budget 4\.0 KiB "
                                         r"\(config\)") as ei:
        _run("  admission: strict\n"
             "  device_memory_budget: 4KiB\n"
             f"  compile_cache: {aot}")
    # the diagnostic must name the levers, not just the numbers
    assert "pipeline_depth" in str(ei.value)
    assert not aot.is_dir() or not list(aot.iterdir())


def test_strict_without_any_budget_refuses():
    # CPU backends report no bytes_limit; strict must not silently
    # admit just because there is nothing to compare against
    with pytest.raises(ValueError, match="budget"):
        _run("  admission: strict")


def test_auto_without_budget_skips_loudly(ref):
    _, stats, _ = ref
    adm = stats.admission
    assert adm is not None and adm["action"] == "no-budget"
    assert adm["budget"] == 0 and adm["overrides"] == {}


def test_auto_admits_within_budget():
    stats, c = _run("  device_memory_budget: 1GiB")
    assert stats.ok
    adm = stats.admission
    assert adm["action"] == "admit" and adm["fits"]
    assert adm["budget_source"] == "config"
    assert adm["estimate"]["per_device"] <= adm["budget"]


def test_auto_over_budget_admits_loudly_and_runs(ref):
    sig_ref, _, _ = ref
    stats, c = _run("  dispatch_segment: 200ms\n"
                    "  state_audit: true\n"
                    "  device_memory_budget: 4KiB")
    assert stats.ok
    adm = stats.admission
    assert adm["action"] == "over" and not adm["fits"]
    assert _sig(stats, c) == sig_ref


def test_auto_degrades_pipeline_depth_preflight(ref):
    sig_ref, _, c_ref = ref
    # a budget BETWEEN the depth-1 and depth-4 footprints: auto must
    # shed depth until the estimate fits, and the shallower run must
    # stay bit-identical (depth is pure host orchestration)
    est1 = capacity.footprint(c_ref.runner.engine,
                              pipeline_depth=1)["per_device"]
    est4 = capacity.footprint(c_ref.runner.engine,
                              pipeline_depth=4)["per_device"]
    assert est1 < est4
    budget = (est1 + est4) // 2
    stats, c = _run("  dispatch_segment: 200ms\n"
                    "  state_audit: true\n"
                    "  pipeline_depth: 4\n"
                    f"  device_memory_budget: {budget}")
    assert stats.ok
    adm = stats.admission
    assert adm["action"] == "degrade" and adm["fits"]
    assert 1 <= adm["overrides"]["pipeline_depth"] < 4
    assert _sig(stats, c) == sig_ref


# ---------------------------------------------------------------------------
# the runtime ladder: deterministic OOM degrades instead of aborting
# ---------------------------------------------------------------------------

def test_deterministic_oom_walks_depth_rung_within_retry_budget(ref):
    sig_ref, _, _ = ref
    # a scripted RESOURCE_EXHAUSTED that REPEATS until a rung engages,
    # against a retry budget of ONE: without the ladder short-circuit
    # (second consecutive identical OOM -> degrade, budget untouched)
    # this run could only escalate
    stats, c = _run(OOM_BASE +
                    "  pipeline_depth: 2\n"
                    "  chaos:\n"
                    "  - {kind: oom, segment: 1}")
    assert stats.ok
    assert stats.degrades == 1
    assert stats.retries <= 1      # the budget was never exhausted
    assert _sig(stats, c) == sig_ref
    kinds = [f["kind"] for f in c.runner.chaos.fired]
    assert "oom" in kinds and "oom_cleared" in kinds


def test_deterministic_oom_at_depth_1_halves_dispatch_segment(ref):
    sig_ref, _, _ = ref
    # no pipeline depth to shed, no ensemble: the ladder's next rung
    # halves the dispatch segment and replays
    stats, c = _run(OOM_BASE +
                    "  chaos:\n"
                    "  - {kind: oom, segment: 1}")
    assert stats.ok
    assert stats.degrades >= 1
    assert stats.retries <= 1
    assert _sig(stats, c) == sig_ref
    cleared = [f for f in c.runner.chaos.fired
               if f["kind"] == "oom_cleared"]
    assert cleared and "dispatch_segment" in cleared[0]["rung"]


def test_compile_seam_oom_walks_ladder(tmp_path, ref):
    sig_ref, _, _ = ref
    # a COLD private cache so the compile actually runs (a warm hit
    # compiles nothing and the seam never fires)
    stats, c = _run(OOM_BASE +
                    "  pipeline_depth: 2\n"
                    f"  compile_cache: {tmp_path / 'aot'}\n"
                    "  chaos:\n"
                    "  - {kind: oom, compile: 0}")
    assert stats.ok
    assert stats.degrades == 1
    assert stats.retries <= 1
    assert _sig(stats, c) == sig_ref
    fired = c.runner.chaos.fired
    assert any(f.get("seam") == "compile" for f in fired
               if f["kind"] == "oom")


# ---------------------------------------------------------------------------
# ensemble replica batches: configured and ladder-driven
# ---------------------------------------------------------------------------

def test_replica_batch_config_bitmatches_full_vmap(tmp_path, ens_full):
    rec = tmp_path / "ENSEMBLE.json"
    c = Controller(load_config_str(
        YAML.format(extra="  dispatch_segment: 200ms")
        + ENS.format(rec=rec) + "  replica_batch: 1\n"))
    stats = c.run()
    assert stats.ok
    f = c.runner.final_state
    for k, want in ens_full.items():
        assert np.array_equal(np.asarray(f[k]), want), k
    assert stats.pipeline["replica_batches"] == 2
    assert stats.pipeline["replica_batch"] == 1
    record = json.loads(rec.read_text())
    assert record["replica_batch"] == 1
    assert record["admission"]["replica_batch"] == 1


def test_oom_walks_replica_batch_rung_bitmatch(tmp_path, ens_full):
    # depth 1, ensemble: the ladder's replica-batch rung re-runs the
    # campaign as sequential batches — bit-identical to the full vmap
    rec = tmp_path / "ENSEMBLE.json"
    c = Controller(load_config_str(
        YAML.format(extra=OOM_BASE +
                    "  chaos:\n"
                    "  - {kind: oom, segment: 1}")
        + ENS.format(rec=rec)))
    stats = c.run()
    assert stats.ok
    assert stats.degrades >= 1
    f = c.runner.final_state
    for k, want in ens_full.items():
        assert np.array_equal(np.asarray(f[k]), want), k
    assert stats.pipeline["replica_batches"] == 2
    cleared = [f for f in c.runner.chaos.fired
               if f["kind"] == "oom_cleared"]
    assert cleared and "replica" in cleared[0]["rung"]


# ---------------------------------------------------------------------------
# estimator honesty: footprint() vs live device bytes mid-run
# ---------------------------------------------------------------------------

def _spy_live(monkeypatch, cls):
    """Sample engine.live_bytes() at every heartbeat boundary (the
    template heartbeats every 200ms), when the run's state actually
    sits on the devices."""
    samples = []
    orig = cls._emit_heartbeats

    def probe(self, now, state):
        samples.append(self.engine.live_bytes())
        return orig(self, now, state)

    monkeypatch.setattr(cls, "_emit_heartbeats", probe)
    return samples


def _honest(samples, engine, depth):
    assert samples
    live = max(samples)
    est = capacity.footprint(engine,
                             pipeline_depth=depth)["per_device"]
    tol = capacity.FOOTPRINT_TOLERANCE
    assert live <= est * tol, (live, est)   # never a blind underestimate
    assert est <= live * tol, (live, est)   # never uselessly conservative


def test_footprint_honest_standalone(monkeypatch):
    gc.collect()
    samples = _spy_live(monkeypatch, DeviceRunner)
    stats, c = _run("  dispatch_segment: 200ms")
    assert stats.ok
    _honest(samples, c.runner.engine, 0)


def test_footprint_honest_pipelined_depth_4(monkeypatch):
    gc.collect()
    samples = _spy_live(monkeypatch, DeviceRunner)
    stats, c = _run("  dispatch_segment: 200ms\n  pipeline_depth: 4")
    assert stats.ok
    _honest(samples, c.runner.engine, 4)


def test_footprint_honest_ensemble(monkeypatch, tmp_path):
    gc.collect()
    samples = _spy_live(monkeypatch, EnsembleRunner)
    c = Controller(load_config_str(
        YAML.format(extra="  dispatch_segment: 200ms")
        + ENS.format(rec=tmp_path / "ENSEMBLE.json")))
    stats = c.run()
    assert stats.ok
    _honest(samples, c.runner.engine, 0)


# ---------------------------------------------------------------------------
# memory observability: heartbeat column + SimStats fields
# ---------------------------------------------------------------------------

def test_heartbeats_and_stats_report_memory(caplog):
    with caplog.at_level(logging.INFO):
        stats, c = _run("  dispatch_segment: 200ms")
    assert stats.ok
    hb = [r.getMessage() for r in caplog.records
          if "[supervise-heartbeat]" in r.getMessage()]
    assert hb and all("mem=" in line for line in hb)
    mem = c.runner.engine.device_memory_stats()
    if mem is None:
        # CPU backends expose no allocator stats: the column reads
        # n/a and the stats fields hold the -1 sentinel
        assert all("mem=n/a" in line for line in hb)
        assert stats.mem_bytes_in_use == -1
        assert stats.mem_budget == -1
    else:
        assert stats.mem_bytes_in_use > 0
        assert stats.mem_budget > 0


def test_ensemble_heartbeats_report_memory(caplog, tmp_path):
    with caplog.at_level(logging.INFO):
        c = Controller(load_config_str(
            YAML.format(extra="  dispatch_segment: 200ms")
            + ENS.format(rec=tmp_path / "ENSEMBLE.json")))
        stats = c.run()
    assert stats.ok
    hb = [r.getMessage() for r in caplog.records
          if "[ensemble-heartbeat]" in r.getMessage()]
    assert hb and all("mem=" in line for line in hb)
