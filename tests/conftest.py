"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(shard_map over a Mesh, all_to_all / all_gather collectives) is exercised
without TPU hardware.

This environment preloads a TPU PJRT plugin via sitecustomize which force-
sets jax's `jax_platforms` config to "axon,cpu" — with exactly one
physical chip behind a relay that admits one client at a time. Tests must
never dial it (concurrent test runs would deadlock on the claim), so we
override the platform list back to cpu-only *before* any backend
initialization, which wins over both the env var and the plugin's write.
"""

import os
import tempfile

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# isolate the default-on AOT compile cache (device/aotcache.py) from
# the user's ~/.cache: tests still share one cache within the session
# (identical engine configs across tests load instead of recompiling)
# but never pollute or depend on state outside the run
os.environ.setdefault("SHADOW_TPU_AOT_DIR",
                      tempfile.mkdtemp(prefix="shadow_tpu_aot_test_"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
