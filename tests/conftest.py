"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(shard_map over a Mesh, all_to_all / all_gather collectives) is exercised
without TPU hardware. The env vars must be set before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
