"""Device-state checkpoint / resume (device/checkpoint.py).

The reference has no checkpoint facility (SURVEY §5) — simulations
run start-to-finish. The device engine's state is an explicit array
pytree, so pause/save/resume is supported and must be bit-identical
to the uninterrupted run: window clamping stays on the global stop
(the same contract as heartbeat segmentation)."""

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

YAML = """
general:
  stop_time: 3s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.1 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.1 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.1 ]
      ]
experimental:
  scheduler_policy: tpu
  event_capacity: 192
  outbox_capacity: 256
{extra}
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_server
      start_time: 10ms
  client:
    quantity: 6
    network_node_id: 1
    processes:
    - path: model:tgen_client
      args: server=server size=200KiB count=3 pause=150ms retry=250ms
      start_time: 100ms
"""


def _run(extra=""):
    c = Controller(load_config_str(YAML.format(extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


def test_pause_save_resume_bitmatches_uninterrupted(tmp_path):
    ck = str(tmp_path / "state.npz")
    full_stats, full_c = _run()
    assert full_stats.ok

    part_stats, _ = _run(
        f"  checkpoint_save: {ck}\n"
        f"  checkpoint_save_time: 1500ms")
    assert part_stats.ok
    # the pause point is mid-run: strictly less work than the full
    # run, and the reported end time is the pause, not the config stop
    assert part_stats.events_executed < full_stats.events_executed
    assert part_stats.end_time == 1_500_000_000

    res_stats, res_c = _run(f"  checkpoint_load: {ck}")
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)

    # the meta carries ALL capacity knobs (a planned resume adopts
    # them — not just the two layout-determining fingerprint ones)
    from shadow_tpu.device import checkpoint
    caps = checkpoint.peek_meta(ck)["capacities"]
    assert set(caps) == {"event_capacity", "outbox_capacity",
                         "exchange_capacity", "exchange_capacity2",
                         "exchange_in_capacity", "outbox_compact"}


def test_tor_pause_resume_bitmatches(tmp_path):
    """Checkpoint/resume on the TOR app family (onion trains,
    relay burst pops, different app-state shape than tgen): a
    mid-bootstrap pause + resume of the small-Tor example must
    bit-match the uninterrupted run."""
    import os
    from shadow_tpu import simtime
    from shadow_tpu.config import load_config

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "tor_small.yaml")
    ck = str(tmp_path / "tor.npz")

    def run(extra=None):
        cfg = load_config(path)
        cfg.general.stop_time = simtime.from_seconds(12.0)
        if extra:
            for k, v in extra.items():
                setattr(cfg.experimental, k, v)
        c = Controller(cfg)
        stats = c.run()
        return stats, c

    full_stats, full_c = run()
    assert full_stats.ok
    run({"checkpoint_save": ck,
         "checkpoint_save_time": simtime.from_seconds(7.0)})
    res_stats, res_c = run({"checkpoint_load": ck})
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)


def test_resume_with_heartbeat_segmentation(tmp_path):
    """Resume under hb/dispatch segmentation still bit-matches (the
    segmented loop starts at the saved t, heartbeat boundaries align
    past it)."""
    ck = str(tmp_path / "state.npz")
    full_stats, full_c = _run()
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1200ms")
    res_stats, res_c = _run(
        f"  checkpoint_load: {ck}\n"
        f"  dispatch_segment: 700ms")
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)


def test_fingerprint_mismatch_rejected(tmp_path):
    ck = str(tmp_path / "state.npz")
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms")
    bad = YAML.replace("seed: 11", "seed: 12")
    with pytest.raises(ValueError, match="does not match"):
        Controller(load_config_str(bad.format(
            extra=f"  checkpoint_load: {ck}"))).run()


def test_topology_edit_rejected(tmp_path):
    """A checkpoint resumed against an edited graph would replay the
    remaining events on different latencies/losses — the topology is
    part of the fingerprint, so the load must refuse."""
    ck = str(tmp_path / "state.npz")
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms")
    bad = YAML.replace('latency "20 ms"', 'latency "25 ms"')
    with pytest.raises(ValueError, match="does not match"):
        Controller(load_config_str(bad.format(
            extra=f"  checkpoint_load: {ck}"))).run()


def test_resume_at_different_burst_width(tmp_path):
    """burst_pops is a trace-invariant perf knob — retuning it across
    a save/resume pair (the on-chip tuning workflow) must neither be
    rejected by the fingerprint nor change the trace."""
    ck = str(tmp_path / "state.npz")
    full_stats, full_c = _run()
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms\n"
         f"  burst_pops: 4")
    res_stats, res_c = _run(f"  checkpoint_load: {ck}\n"
                            f"  burst_pops: 8")
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)


def test_bandwidth_edit_rejected(tmp_path):
    """Per-host bandwidths steer packet timing (model NIC) — they are
    fingerprinted too, so an edited-bandwidth resume refuses."""
    ck = str(tmp_path / "state.npz")
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms")
    bad = YAML.replace('id 1 bandwidth_down "1 Gbit"',
                       'id 1 bandwidth_down "500 Mbit"')
    with pytest.raises(ValueError, match="does not match"):
        Controller(load_config_str(bad.format(
            extra=f"  checkpoint_load: {ck}"))).run()


def test_unwritable_save_path_fails_fast(tmp_path):
    with pytest.raises(ValueError, match="not writable"):
        _run("  checkpoint_save: "
             f"{tmp_path}/no-such-dir/state.npz")


def test_save_time_without_path_rejected():
    with pytest.raises(ValueError, match="checkpoint_save_time"):
        load_config_str(YAML.format(
            extra="  checkpoint_save_time: 1s"))


def test_checkpoint_requires_device_policy():
    with pytest.raises(ValueError, match="scheduler_policy: tpu"):
        load_config_str(YAML.format(
            extra="  checkpoint_save: /tmp/x.npz").replace(
            "scheduler_policy: tpu", "scheduler_policy: serial"))


def test_resume_at_or_past_stop_rejected(tmp_path):
    ck = str(tmp_path / "state.npz")
    _run(f"  checkpoint_save: {ck}")     # pauses at stop_time
    with pytest.raises(ValueError, match="nothing to resume"):
        _run(f"  checkpoint_load: {ck}")


def test_resume_toward_different_stop_rejected(tmp_path):
    """The saved prefix's windows were clamped on the run's global
    stop (final_stop, stamped in the npz meta) — resuming toward a
    different stop would not bit-match an uninterrupted run at that
    stop, so the load must refuse the mismatch."""
    ck = str(tmp_path / "state.npz")
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms")
    bad = YAML.replace("stop_time: 3s", "stop_time: 4s")
    with pytest.raises(ValueError, match="stop"):
        Controller(load_config_str(bad.format(
            extra=f"  checkpoint_load: {ck}"))).run()


def test_pre_telemetry_checkpoint_loads(tmp_path):
    """Checkpoints saved before the occ_* telemetry leaves existed
    lack them in the npz key list; the load fills the missing
    counters from the freshly-initialized template (zeros) instead of
    rejecting, and the resumed trace still bit-matches."""
    import json

    import numpy as np

    ck = str(tmp_path / "state.npz")
    full_stats, full_c = _run()
    _run(f"  checkpoint_save: {ck}\n"
         f"  checkpoint_save_time: 1500ms")

    with np.load(ck, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        saved = {k: z[f"leaf_{i}"]
                 for i, k in enumerate(meta["keys"])}
    meta["keys"] = [k for k in meta["keys"] if "'occ_" not in k]
    arrays = {f"leaf_{i}": saved[k]
              for i, k in enumerate(meta["keys"])}
    with open(ck, "wb") as f:
        np.savez_compressed(f, __meta__=json.dumps(meta), **arrays)

    res_stats, res_c = _run(f"  checkpoint_load: {ck}")
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)

    # a non-telemetry leaf going missing must still refuse loudly
    meta2 = dict(meta, keys=[k for k in meta["keys"]
                             if "'overflow'" not in k])
    arrays2 = {f"leaf_{i}": saved[k]
               for i, k in enumerate(meta2["keys"])}
    with open(ck, "wb") as f:
        np.savez_compressed(f, __meta__=json.dumps(meta2), **arrays2)
    with pytest.raises(ValueError, match="state layout changed"):
        _run(f"  checkpoint_load: {ck}")


@pytest.mark.slow
def test_resume_adopts_saved_capacities_under_plan(tmp_path,
                                                   monkeypatch):
    """capacity_plan under checkpoint_load skips planning and adopts
    the SAVED engine's capacities (peeked from the npz fingerprint):
    a checkpoint written by a planner-sized engine must stay loadable
    even though the planned capacities differ from the config's
    static knobs — and the resumed pair must still bit-match the
    uninterrupted run."""
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    ck = str(tmp_path / "state.npz")
    full_stats, full_c = _run()

    # save under an active plan: the saved fingerprint carries the
    # planner's capacities, not event_capacity: 192 from the YAML
    plan = ("  capacity_plan: auto\n"
            "  capacity_warmup: 2500ms\n")
    save_stats, _ = _run(plan +
                         f"  checkpoint_save: {ck}\n"
                         f"  checkpoint_save_time: 1500ms")
    assert save_stats.ok

    res_stats, res_c = _run(plan + f"  checkpoint_load: {ck}")
    assert res_stats.ok
    assert _sig(res_stats, res_c) == _sig(full_stats, full_c)

    # and a static-config resume of that planned save works too
    res2_stats, res2_c = _run(f"  checkpoint_load: {ck}\n"
                              f"  capacity_plan: auto")
    assert res2_stats.ok
    assert _sig(res2_stats, res2_c) == _sig(full_stats, full_c)
