"""Deterministic fault injection (shadow_tpu/faults.py).

The fault layer's whole contract is bit-identity: the epoch table is
compiled once at load and every backend — CPU binary search, hybrid
device judge, full device engine — selects the active epoch by the
packet's send time, so fault-injected traces match across
serial/thread/hybrid/tpu exactly like fault-free ones. These tests pin
the compiler's semantics, the cross-policy determinism matrix, host
crash/restart behavior, and checkpoint/resume across a fault window.
"""

import numpy as np
import pytest

from shadow_tpu import simtime
from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.faults import (
    FaultEvent,
    compile_link_faults,
    resolve_host_faults,
)
from shadow_tpu.topology.graph import Topology

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 2 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
  edge [ source 0 target 1 latency "20 ms" packet_loss 0.0 ]
  edge [ source 1 target 2 latency "30 ms" packet_loss 0.0 ]
  edge [ source 0 target 2 latency "80 ms" packet_loss 0.0 ]
]"""

MS = simtime.SIMTIME_ONE_MILLISECOND
S = simtime.SIMTIME_ONE_SECOND


def _top():
    return Topology.from_gml(GML)


# ---------------------------------------------------------------------
# epoch-table compiler
# ---------------------------------------------------------------------
def test_epoch_table_base_epoch_is_healthy_matrices():
    top = _top()
    ft = compile_link_faults(top, [
        FaultEvent(kind="link_down", time=2 * S, source=0, target=1),
        FaultEvent(kind="link_up", time=3 * S, source=0, target=1),
    ])
    assert ft.n_epochs == 3
    np.testing.assert_array_equal(ft.times, [0, 2 * S, 3 * S])
    # epoch 0 and the post-restore epoch ARE the base matrices
    np.testing.assert_array_equal(ft.latency_ns[0], top.latency_ns)
    np.testing.assert_array_equal(ft.reliability[0], top.reliability)
    np.testing.assert_array_equal(ft.latency_ns[2], top.latency_ns)
    np.testing.assert_array_equal(ft.reliability[2], top.reliability)


def test_link_down_reroutes_and_cuts():
    top = _top()
    # dropping 0-1 leaves 0-2-1 (80+30 ms); reliability stays 1
    ft = compile_link_faults(top, [
        FaultEvent(kind="link_down", time=1 * S, source=0, target=1)])
    assert ft.lookup(0, 0, 1) == (20 * MS, 1.0)
    lat, rel = ft.lookup(1 * S, 0, 1)
    assert lat == 110 * MS and rel == 1.0
    # dropping BOTH 0-1 and 0-2 isolates vertex 0 from the rest:
    # reliability 0 (undeliverable), latency keeps the base value
    ft2 = compile_link_faults(top, [
        FaultEvent(kind="link_down", time=1 * S, source=0, target=1),
        FaultEvent(kind="link_down", time=1 * S, source=0, target=2)])
    lat, rel = ft2.lookup(1 * S, 0, 2)
    assert rel == 0.0
    assert lat == int(top.latency_ns[0, 2])
    # self paths still work on the isolated vertex (loopback is not
    # the network)
    _, self_rel = ft2.lookup(1 * S, 0, 0)
    assert self_rel > 0.0


def test_degrade_scales_latency_and_reliability():
    top = _top()
    ft = compile_link_faults(top, [
        FaultEvent(kind="degrade", time=1 * S, duration=1 * S,
                   source=0, target=1, latency_multiplier=3.0,
                   extra_packet_loss=0.25)])
    np.testing.assert_array_equal(ft.times, [0, 1 * S, 2 * S])
    lat, rel = ft.lookup(1 * S, 0, 1)
    assert lat == 60 * MS
    assert rel == pytest.approx(0.75, abs=1e-6)
    # window end restores
    assert ft.lookup(2 * S, 0, 1) == (20 * MS, 1.0)
    # epoch selection is by send time: just before the window start
    # the base values hold
    assert ft.lookup(1 * S - 1, 0, 1) == (20 * MS, 1.0)


def test_compile_validation_errors():
    top = _top()
    with pytest.raises(ValueError, match="no such edge"):
        compile_link_faults(top, [FaultEvent(
            kind="link_down", time=0, source=1, target=1)])
    with pytest.raises(ValueError, match="unknown vertex"):
        compile_link_faults(top, [FaultEvent(
            kind="link_down", time=0, source=0, target=9)])
    with pytest.raises(ValueError, match="already down"):
        compile_link_faults(top, [
            FaultEvent(kind="link_down", time=0, source=0, target=1),
            FaultEvent(kind="link_down", time=1, source=1, target=0)])
    with pytest.raises(ValueError, match="without a preceding"):
        compile_link_faults(top, [FaultEvent(
            kind="link_up", time=1, source=0, target=1)])
    with pytest.raises(ValueError, match="ambiguous"):
        compile_link_faults(top, [
            FaultEvent(kind="link_down", time=5, source=0, target=1),
            FaultEvent(kind="link_up", time=5, source=0, target=1)])
    with pytest.raises(ValueError, match="duration"):
        compile_link_faults(top, [FaultEvent(
            kind="degrade", time=0, source=0, target=1,
            latency_multiplier=2.0)])
    with pytest.raises(ValueError, match="changes nothing"):
        compile_link_faults(top, [FaultEvent(
            kind="degrade", time=0, duration=1, source=0, target=1)])
    assert compile_link_faults(top, []) is None


def test_resolve_host_faults_validation():
    ids = {"a": 0, "b": 1}
    out = resolve_host_faults([
        FaultEvent(kind="host_restart", time=2 * S, host="a"),
        FaultEvent(kind="host_crash", time=1 * S, host="a"),
    ], ids)
    assert out == [(1 * S, 0, "host_crash"), (2 * S, 0, "host_restart")]
    with pytest.raises(ValueError, match="unknown host"):
        resolve_host_faults(
            [FaultEvent(kind="host_crash", time=0, host="zz")], ids)
    with pytest.raises(ValueError, match="already crashed"):
        resolve_host_faults([
            FaultEvent(kind="host_crash", time=0, host="a"),
            FaultEvent(kind="host_crash", time=1, host="a")], ids)
    with pytest.raises(ValueError, match="without a preceding"):
        resolve_host_faults(
            [FaultEvent(kind="host_restart", time=0, host="b")], ids)


def test_schema_rejects_malformed_fault_entries():
    base = """
general: {stop_time: 1s}
network:
  faults:
    - %s
hosts:
  a:
    processes: [{path: model:phold}]
"""
    for bad, msg in [
        ("{kind: nope, time: 1s}", "not one of"),
        ("{kind: link_down, time: 1s}", "source"),
        ("{kind: host_crash, time: 1s}", "host"),
        ("{kind: link_down, time: 1s, source: 0, target: 1, "
         "host: a}", "only valid"),
        ("{kind: link_down, source: 0, target: 1}", "time"),
        ("{kind: host_crash, time: 1s, host: a, duration: 1s}",
         "only valid"),
        ("{kind: link_down, time: 1s, source: 0, target: 1, "
         "latency_multiplier: 2}", "only valid for degrade"),
    ]:
        with pytest.raises(ValueError, match=msg):
            load_config_str(base % bad)


# ---------------------------------------------------------------------
# cross-policy determinism matrix
# ---------------------------------------------------------------------
FAULT_YAML = """
general:
  stop_time: 8s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
      ]
  faults:
{faults}
experimental:
  scheduler_policy: {policy}
  event_capacity: 256
  outbox_capacity: 256
{extra}
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_server
      start_time: 10ms
  client:
    quantity: 3
    network_node_id: 1
    processes:
    - path: model:tgen_client
      args: server=server size=200KiB count=40 pause=50ms retry=300ms
      start_time: 100ms
"""

LINK_FAULTS = """\
    - {kind: degrade, time: 2500ms, duration: 1s, source: 0,
       target: 1, latency_multiplier: 3, extra_packet_loss: 0.2}
    - {kind: link_down, time: 4s, source: 0, target: 1}
    - {kind: link_up, time: 5s, source: 0, target: 1}
"""

CRASH_FAULTS = LINK_FAULTS + """\
    - {kind: host_crash, time: 3s, host: client0}
    - {kind: host_restart, time: 5500ms, host: client0}
"""


def _run(policy, faults, extra=""):
    yaml = FAULT_YAML.format(policy=policy, faults=faults, extra=extra)
    c = Controller(load_config_str(yaml))
    stats = c.run()
    assert stats.ok
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


def test_link_faults_bit_identical_cpu_and_hybrid_judge():
    """A fault-injected tgen run produces bit-identical traces on the
    CPU netmodel and the batched device judge (epoch select inside
    the jitted batch)."""
    base = _sig(*_run("serial", LINK_FAULTS))
    assert base[2] > 0          # the outage/degrade really dropped
    for policy in ("thread", "hybrid"):
        assert _sig(*_run(policy, LINK_FAULTS)) == base, policy


@pytest.mark.slow
def test_link_faults_bit_identical_device_engine():
    """The acceptance bar's device leg: the full device engine (epoch
    gather inside the scan) matches the CPU netmodel bit for bit.
    Slow-marked for its engine compile; the determinism CI rung
    additionally pins serial vs thread vs tpu on
    examples/tgen_faults.yaml."""
    base = _sig(*_run("serial", LINK_FAULTS))
    assert _sig(*_run("tpu", LINK_FAULTS)) == base


@pytest.mark.slow
def test_link_faults_device_strategy_invariant():
    """Epoch selection composes with the gatherless merge/pop
    strategies: traces stay identical whichever path computes them."""
    base = _sig(*_run("tpu", LINK_FAULTS))
    alt = _sig(*_run("tpu", LINK_FAULTS,
                     "  merge_strategy: global\n"
                     "  pop_strategy: onehot\n"
                     "  judge_placement: flush"))
    assert alt == base


def test_host_crash_restart_deterministic_and_recovers():
    s_stats, s_c = _run("serial", CRASH_FAULTS)
    base = _sig(s_stats, s_c)
    crashed = s_c.sim.hosts[1]          # client0
    assert crashed.name == "client0"
    assert crashed.events_quarantined > 0
    assert not crashed.crashed          # restarted
    # the respawned process booted fresh and made progress again:
    # downloads_done restarts from zero on the NEW app object
    assert crashed.app.downloads_done > 0
    # hybrid (and tpu, which falls back to hybrid for host faults)
    # matches the serial oracle bit for bit
    for policy in ("thread", "hybrid", "tpu"):
        assert _sig(*_run(policy, CRASH_FAULTS)) == base, policy


def test_tpu_policy_falls_back_to_hybrid_on_host_faults():
    _, c = _run("tpu", CRASH_FAULTS)
    assert c.runner is None             # hybrid fallback engaged
    assert c.manager is not None
    assert c.manager.net_judge is not None


def test_faulted_run_twice_identical():
    a = _sig(*_run("serial", CRASH_FAULTS))
    b = _sig(*_run("serial", CRASH_FAULTS))
    assert a == b


RESTART_EDGE_YAML = """
general:
  stop_time: {stop}
  seed: 9
  {hb}
network:
  faults:
    - {{kind: host_crash, time: 1s, host: late}}
    - {{kind: host_restart, time: 2s, host: late}}
hosts:
  late:
    processes:
    - path: model:phold
      args: msgload=2
      start_time: {start}
      {stop_line}
  peer:
    processes:
    - path: model:phold
      args: msgload=2
      start_time: 100ms
"""


def test_restart_does_not_double_boot_future_start():
    """A process whose configured start_time is AFTER the restart must
    boot exactly once — via its still-queued original BOOT event, not
    an extra restart-time boot."""
    boots = []
    from shadow_tpu.core.event import KIND_BOOT

    cfg = load_config_str(RESTART_EDGE_YAML.format(
        stop="4s", start="3s", stop_line="", hb=""))
    c = Controller(cfg)
    c.manager.on_event_hook = (
        lambda ev: boots.append((ev.time, ev.dst_host))
        if ev.kind == KIND_BOOT else None)
    assert c.run().ok
    late_boots = [t for t, hid in boots if hid == 0]
    assert late_boots == [3 * S]


def test_restart_skips_process_whose_stop_passed():
    """A process whose stop_time elapsed while the host was down
    stays dead at restart (a real init would not relaunch it)."""
    cfg = load_config_str(RESTART_EDGE_YAML.format(
        stop="4s", start="100ms", stop_line="stop_time: 1500ms",
        hb=""))
    c = Controller(cfg)
    assert c.run().ok
    late = c.sim.hosts[0]
    assert late.apps == [None]       # placeholder keeps indices
    assert late.app is None


def test_restart_reseeds_heartbeats():
    """The crash quarantines the self-rescheduling heartbeat task;
    restart must re-seed the chain so ticks resume after the gap."""
    from shadow_tpu.core.event import KIND_TASK

    cfg = load_config_str(RESTART_EDGE_YAML.format(
        stop="5s", start="100ms", stop_line="",
        hb="heartbeat_interval: 500ms"))
    c = Controller(cfg)
    ticks = []
    c.manager.on_event_hook = (
        lambda ev: ticks.append(ev.time)
        if ev.kind == KIND_TASK and ev.dst_host == 0 else None)
    assert c.run().ok
    # ticks ran before the 1s crash, none during [1s, 2s) (the chain
    # task was quarantined), and resumed at the first interval
    # boundary after the 2s restart
    assert any(t < 1 * S for t in ticks)
    assert not [t for t in ticks if 1 * S < t < 2 * S]
    post = [t for t in ticks if t >= 2 * S]
    assert post and post[0] == 2 * S + 500 * MS
    # exactly ONE chain: every resumed boundary ticks once
    assert len(post) == len(set(post))


def test_short_outage_does_not_duplicate_heartbeats():
    """A crash window that no heartbeat tick surfaced in leaves the
    original (still-queued) chain alive — the restart must NOT seed a
    second one, or every later interval would tick twice."""
    from shadow_tpu.core.event import KIND_TASK

    yaml = RESTART_EDGE_YAML.format(
        stop="5s", start="100ms", stop_line="",
        hb="heartbeat_interval: 1s").replace(
        "time: 1s, host: late", "time: 1100ms, host: late").replace(
        "time: 2s, host: late", "time: 1300ms, host: late")
    c = Controller(load_config_str(yaml))
    ticks = []
    c.manager.on_event_hook = (
        lambda ev: ticks.append(ev.time)
        if ev.kind == KIND_TASK and ev.dst_host == 0 else None)
    assert c.run().ok
    # the 2s/3s/4s ticks each fire exactly once
    assert sorted(ticks) == [1 * S, 2 * S, 3 * S, 4 * S]


# ---------------------------------------------------------------------
# checkpoint across a fault window
# ---------------------------------------------------------------------
@pytest.mark.slow
def test_checkpoint_resume_across_fault_window(tmp_path):
    ck = str(tmp_path / "state.npz")
    full = _sig(*_run("tpu", LINK_FAULTS))
    part_stats, _ = _run("tpu", LINK_FAULTS,
                         f"  checkpoint_save: {ck}\n"
                         "  checkpoint_save_time: 3s")
    assert part_stats.end_time == 3 * S
    res = _sig(*_run("tpu", LINK_FAULTS, f"  checkpoint_load: {ck}"))
    assert res == full

    # the fault schedule is fingerprinted into the npz meta: resuming
    # against an EDITED schedule must be rejected, not silently
    # diverge
    from shadow_tpu.device import checkpoint
    assert checkpoint.peek_meta(ck)["fingerprint"]["fault_epochs"] == 5
    edited = LINK_FAULTS.replace("time: 4s", "time: 3500ms")
    with pytest.raises(ValueError, match="does not match"):
        _run("tpu", edited, f"  checkpoint_load: {ck}")


@pytest.mark.slow
def test_fault_free_fingerprint_unchanged(tmp_path):
    """Fault-free checkpoints keep the pre-fault-layer fingerprint
    surface (no fault_epochs key, no epoch_times in the world hash),
    so existing saved states stay loadable."""
    ck = str(tmp_path / "nofault.npz")
    yaml = FAULT_YAML.format(policy="tpu", faults="    []",
                             extra=(f"  checkpoint_save: {ck}\n"
                                    "  checkpoint_save_time: 3s"))
    c = Controller(load_config_str(yaml))
    assert c.run().ok
    from shadow_tpu.device import checkpoint
    assert "fault_epochs" not in checkpoint.peek_meta(ck)["fingerprint"]
