"""fork/wait4 + virtual signal delivery for managed processes.

VERDICT round-3 item #7: a managed program can fork real children
(each a full virtual process: own vpid, fd table sharing the parent's
file descriptions, COW memory), wait for them (blocking wait4 with
zombie reaping + ECHILD), and exchange virtual signals (rt_sigaction
registry, kill/tgkill, handler invocation at syscall boundaries via
IPC_SIGNAL, EINTR on interrupted blocking syscalls). Reference:
src/main/host/process.c:457-651, syscall/signal.c, kernel exit.c.
"""

import os
import subprocess

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

PLUGIN_DIR = os.path.join(os.path.dirname(__file__), "plugins")

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
]"""


def _indent(text: str, n: int) -> str:
    return "\n".join(" " * n + line for line in text.splitlines())


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("plugins")
    built = {}
    for name in ("fork_check", "signal_check", "sigmask_check",
                 "waitid_check"):
        exe = out / name
        subprocess.run(
            ["cc", "-O1", "-pthread", "-o", str(exe),
             os.path.join(PLUGIN_DIR, f"{name}.c")],
            check=True, capture_output=True)
        built[name] = str(exe)
    return built


def run_one(exe: str, data: str, stop: str = "30s"):
    cfg = load_config_str(f"""
general:
  stop_time: {stop}
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
  alice:
    network_node_id: 0
    processes:
    - path: {exe}
      start_time: 1s
""")
    return Controller(cfg).run()


def stdout_of(data: str, host: str, exe: str) -> str:
    d = os.path.join(data, "hosts", host)
    for f in sorted(os.listdir(d)):
        if f.startswith(exe) and f.endswith(".stdout"):
            with open(os.path.join(d, f)) as fh:
                return fh.read()
    raise FileNotFoundError(f"no stdout for {exe} in {d}")


def test_fork_wait_exit_status(bins, tmp_path):
    data = str(tmp_path / "shadow.data")
    stats = run_one(bins["fork_check"], data)
    assert stats.ok
    out = stdout_of(data, "alice", "fork_check").splitlines()
    assert out[0] == "child pid!=parent 1 ppid_ok 1"
    assert out[1] == "parent sees child 1"
    # the child slept 200 ms of SIMULATED time before exiting; the
    # parent's blocking wait returns at that exact simulated instant
    assert out[2] == "wait ret_ok 1 exited 1 code 42 t_ms 200"
    assert out[3] == "second ok 1 code 7"
    assert out[4] == "echild 1"


def test_fork_deterministic(bins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        stats = run_one(bins["fork_check"], data)
        assert stats.ok
        outs.append(stdout_of(data, "alice", "fork_check"))
    assert outs[0] == outs[1]


def test_multi_process_host(bins, tmp_path):
    """Several real processes on ONE simulated host (the reference's
    hosts run arbitrary process lists, process.c:457): both boot at
    their own start times and produce independent stdout."""
    data = str(tmp_path / "shadow.data")
    cfg = load_config_str(f"""
general:
  stop_time: 30s
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
  alice:
    network_node_id: 0
    processes:
    - path: {bins['fork_check']}
      start_time: 1s
    - path: {bins['signal_check']}
      start_time: 2s
""")
    stats = Controller(cfg).run()
    assert stats.ok
    out1 = stdout_of(data, "alice", "fork_check")
    out2 = stdout_of(data, "alice", "signal_check")
    assert "echild 1" in out1
    assert "done" in out2


def test_signals_self_cross_and_eintr(bins, tmp_path):
    data = str(tmp_path / "shadow.data")
    stats = run_one(bins["signal_check"], data)
    assert stats.ok
    out = stdout_of(data, "alice", "signal_check").splitlines()
    # SIGUSR1 handler ran AND its own trapped syscall was serviced
    assert out[0] == "self got1 10 handler_syscall_ok 1"
    assert out[1] == "ignored ok"
    # child's SIGUSR2 at +150 ms sim interrupted the 10 s nanosleep:
    # SA_SIGINFO handler got (sig, siginfo) -> 12+1; EINTR; exact time
    assert out[2] == "eintr 1 errno_ok 1 got2 13 t_ms 150"
    # SIGKILL'd sleeping child: WIFSIGNALED with WTERMSIG 9, reaped at
    # the kill instant (+50 ms)
    assert out[3] == "sigkill ok 1 signaled 1 sig 9 t_ms 50"
    assert out[4] == "done"


def test_sigmask_pending_suspend_timedwait(bins, tmp_path):
    """The blocked-signal contract (ref signal.c rt_sigprocmask /
    rt_sigpending / rt_sigsuspend / rt_sigtimedwait): blocked signals
    stay pending and deliver at the unblock boundary; sigsuspend swaps
    the mask atomically and EINTRs after one handler; sigtimedwait
    consumes a queued signal with no handler, or times out with EAGAIN
    at the exact simulated deadline."""
    data = str(tmp_path / "shadow.data")
    stats = run_one(bins["sigmask_check"], data)
    assert stats.ok
    out = stdout_of(data, "alice", "sigmask_check").splitlines()
    assert out[0] == "blocked 1 pending 1 after_unblock 1"
    assert out[1] == "sigsuspend 1 errno_ok 1 got2 1 mask_restored 1"
    assert out[2] == "sigtimedwait 1 si_signo 15 handler_ran 0 t_ms 100"
    # blocked default-ignore signal queued BEFORE the wait began
    # (kernel prepare_signal semantics; the SIGCHLD reaper idiom)
    assert out[3] == "reaper 1 instant 1"
    assert out[4] == "timeout 1 errno_ok 1 t_ms 250"
    # ppoll's temp mask admits the signal mid-wait; block returns after
    assert out[5] == "ppoll_eintr 1 got1 1 t_ms 80 mask_back 1"
    # pthread_kill at a blocking thread: held on that thread, the
    # unblocked main thread never runs it
    assert out[6] == "directed held 1 delivered 1"
    assert out[7] == "main_held 1"
    assert out[8] == "done"


def test_sigmask_deterministic(bins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        stats = run_one(bins["sigmask_check"], data)
        assert stats.ok
        outs.append(stdout_of(data, "alice", "sigmask_check"))
    assert outs[0] == outs[1]


@pytest.fixture(scope="module")
def exec_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("exec_plugins")
    built = {}
    for name in ("exec_check", "exec_target"):
        exe = out / name
        subprocess.run(
            ["cc", "-O1", "-o", str(exe),
             os.path.join(PLUGIN_DIR, f"{name}.c")],
            check=True, capture_output=True)
        built[name] = str(exe)
    return built


def run_exec(bins, data: str):
    cfg = load_config_str(f"""
general:
  stop_time: 30s
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
hosts:
  alice:
    network_node_id: 0
    processes:
    - path: {bins['exec_check']}
      args: {bins['exec_target']}
      start_time: 1s
""")
    return Controller(cfg).run()


def test_execve_managed(exec_bins, tmp_path):
    """A managed process fork+execs another program and the NEW image
    stays managed: same virtual pid, continuous simulated time, exit
    status through wait4; a failed exec leaves the old image running
    (ref process.c exec handling + kernel exec semantics)."""
    data = str(tmp_path / "shadow.data")
    stats = run_exec(exec_bins, data)
    assert stats.ok
    out = stdout_of(data, "alice", "exec_check").splitlines()
    assert out[0] == "badexec 1 errno_ok 1"
    pre = next(l for l in out if l.startswith("child pre-exec"))
    tgt = next(l for l in out if l.startswith("target pid"))
    pre_pid = int(pre.split()[3])
    tgt_pid = int(tgt.split()[2])
    assert pre_pid == tgt_pid          # vpid survives the exec
    assert tgt.split()[6] == "hello"   # argv crossed
    t_start = int(tgt.split()[-1])
    done = next(l for l in out if l.startswith("target done"))
    assert int(done.split()[-1]) == t_start + 70   # sim clock continues
    # FD_CLOEXEC virtual fd closed by the exec; plain fd survives
    clo = next(l for l in out if l.startswith("cloexec"))
    assert clo == "cloexec keep 1 gone 1"
    reap = next(l for l in out if l.startswith("reap"))
    # exit code 33 reaped at fork+40ms(pre-exec sleep)+70ms(target)
    assert reap == "reap ok 1 exited 1 code 33 t_ms 110"
    assert out[-1] == "done"


def test_execve_deterministic(exec_bins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        stats = run_exec(exec_bins, data)
        assert stats.ok
        outs.append(stdout_of(data, "alice", "exec_check"))
    assert outs[0] == outs[1]


@pytest.mark.parametrize("method", ["preload", "ptrace"])
def test_waitid_virtual_children(bins, tmp_path, method):
    """waitid (modern glibc posix_spawn's wait): WNOHANG on a live
    child, WNOWAIT peeking without reaping, CLD_EXITED siginfo, and
    ECHILD after the reap — over VIRTUAL pids on both backends."""
    data = str(tmp_path / "shadow.data")
    cfg = load_config_str(f"""
general:
  stop_time: 30s
  seed: 1
  data_directory: {data}
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
experimental:
  interpose_method: {method}
hosts:
  alice:
    network_node_id: 0
    processes:
    - path: {bins['waitid_check']}
      start_time: 1s
""")
    stats = Controller(cfg).run()
    assert stats.ok
    out = stdout_of(data, "alice", "waitid_check").splitlines()
    assert out[0] == "nohang r=0 pid=0"
    assert out[1] == "nowait r=0 pid_match=1 code_exited=1 status=42"
    assert out[2] == "reap r=0 pid_match=1 status=42"
    assert out[3] == "after r=-1 echild=1"
    assert out[4] == "done"
