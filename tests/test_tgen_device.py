"""tgen device twin vs CPU serial oracle: identical event traces.

Extends the phold equivalence argument (test_device_engine.py) to the
benchmark-ladder workload: chunked pull-based bulk downloads with a
client/server role mix on one vectorized device app."""

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

TGEN_YAML = """
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]
        edge [ source 0 target 1 latency "20 ms" packet_loss {loss} ]
        edge [ source 1 target 1 latency "10 ms" packet_loss {loss} ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 192
  outbox_capacity: 256
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_server
      start_time: 10ms
  client:
    quantity: {clients}
    network_node_id: 1
    processes:
    - path: model:tgen_client
      args: server=server size={size} count={count} pause=200ms {extra}
      start_time: 100ms
"""


def _run(policy, seed=1, loss=0.0, clients=4, size="200KiB", count=2,
         stop="10s", extra=""):
    yaml = TGEN_YAML.format(policy=policy, seed=seed, loss=loss,
                            clients=clients, size=size, count=count,
                            stop=stop, extra=extra)
    c = Controller(load_config_str(yaml))
    stats = c.run()
    return stats, c.sim.hosts


@pytest.mark.parametrize("loss,extra",
                         [(0.0, ""), (0.02, "retry=500ms"),
                          # heavy loss + tight retries: duplicate
                          # trains in flight, including stale trains a
                          # full window back (the u32 shift-clip edge)
                          (0.25, "retry=120ms")])
def test_tgen_device_matches_serial_oracle(loss, extra):
    s_stats, s_hosts = _run("serial", loss=loss, extra=extra)
    d_stats, d_hosts = _run("tpu", loss=loss, extra=extra)
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.packets_sent == d_stats.packets_sent
    assert s_stats.packets_dropped == d_stats.packets_dropped
    for sh, dh in zip(s_hosts, d_hosts):
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_merge_strategy_identical_traces():
    """Global double-sort merge vs window merge on the train-sending
    tgen app with real loss (partial trains, retries) on the 8-device
    mesh — the TPU-default flush path pinned against the CPU-tuned
    one."""
    outs = {}
    for strategy in ("window", "global"):
        yaml = TGEN_YAML.format(policy="tpu", seed=11, loss=0.15,
                                clients=6, size="300KiB", count=2,
                                stop="10s", extra="retry=150ms")
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  merge_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["window"] == outs["global"]


def test_pop_strategy_identical_traces():
    """One-hot head reads vs take_along_axis on the train-sending
    tgen app — the burst-pop (P>1) _take_heads path included, since
    tgen servers declare burst pops. Bit-identical traces required."""
    outs = {}
    for strategy in ("gather", "onehot"):
        yaml = TGEN_YAML.format(policy="tpu", seed=11, loss=0.15,
                                clients=6, size="300KiB", count=2,
                                stop="10s", extra="retry=150ms")
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  pop_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["gather"] == outs["onehot"]


def test_burst_width_identical_traces():
    """Burst lane width is a pure performance knob: per-host pop
    order is (t, src, seq) at any width, so traces of width 1 / 4 /
    the app default (8) must be bit-identical."""
    outs = {}
    for bp in (1, 4, 8):
        yaml = TGEN_YAML.format(policy="tpu", seed=11, loss=0.15,
                                clients=6, size="300KiB", count=2,
                                stop="10s", extra="retry=150ms")
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  burst_pops: {bp}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, bp
        outs[bp] = (stats.events_executed, stats.packets_sent,
                    stats.packets_dropped,
                    [h.trace_checksum for h in c.sim.hosts])
    assert outs[1] == outs[4] == outs[8]


def test_judge_placement_identical_traces():
    """Flush-hoisted network judgment (one batched judge per phase)
    vs the legacy in-step judgment: same drop-roll keys, same delivery
    times, bit-identical traces — on the train-sending tgen app with
    real loss (duplicates, retries, partial trains) and on the
    8-device mesh. The hoist is the TPU-default path; this pins its
    equivalence on the CPU mesh."""
    outs = {}
    for placement in ("step", "flush"):
        yaml = TGEN_YAML.format(policy="tpu", seed=11, loss=0.15,
                                clients=6, size="300KiB", count=2,
                                stop="10s", extra="retry=150ms")
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  judge_placement: {placement}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, placement
        outs[placement] = (stats.events_executed, stats.packets_sent,
                           stats.packets_dropped,
                           [h.trace_checksum for h in c.sim.hosts])
    assert outs["step"] == outs["flush"]


def test_tgen_cpu_clients_complete_downloads():
    stats, hosts = _run("serial", clients=3, size="100KiB", count=3)
    for h in hosts[1:]:
        assert h.app.downloads_done == 3
        assert h.app.bytes_received >= 3 * 100 * 1024
    assert stats.ok


def test_tgen_lossy_retry_completes():
    _, hosts = _run("serial", loss=0.05, clients=2, size="50KiB",
                    count=1, extra="retry=300ms")
    for h in hosts[1:]:
        assert h.app.downloads_done == 1


HET_YAML = """
general:
  stop_time: 10s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.02 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.02 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.02 ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 192
  outbox_capacity: 256
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: model:tgen_server, start_time: 10ms}}
  fast:
    quantity: 3
    network_node_id: 1
    processes:
    - {{path: model:tgen_client,
       args: server=server size=200KiB count=3 pause=100ms retry=300ms,
       start_time: 100ms}}
  slow:
    quantity: 3
    network_node_id: 1
    processes:
    - {{path: model:tgen_client,
       args: server=server size=200KiB count=1 pause=900ms retry=800ms,
       start_time: 200ms}}
"""


def test_tgen_heterogeneous_client_args_on_device():
    """count/pause/retry vary per host (the tor_large/tornettools
    shape); the device twin's per-host arg arrays must bit-match the
    serial oracle. Only `size` (the servers' response shape) must
    stay uniform."""
    outs = {}
    for policy in ("serial", "tpu"):
        c = Controller(load_config_str(HET_YAML.format(policy=policy)))
        stats = c.run()
        assert stats.ok, policy
        outs[policy] = ([h.trace_checksum for h in c.sim.hosts],
                        stats.packets_sent, stats.packets_dropped)
    assert outs["serial"] == outs["tpu"]


def test_tgen_heterogeneous_size_still_refused():
    yaml = HET_YAML.format(policy="tpu").replace(
        "size=200KiB count=1", "size=100KiB count=1")
    with pytest.raises(ValueError, match="size.*must match"):
        Controller(load_config_str(yaml))


def test_outbox_compact_trace_invariant_and_loud_overflow():
    """outbox_compact is a pure flush-cost knob: with compaction
    forced on (width ample) the device trace is bit-identical to the
    uncompacted run; with a width below the busiest host's emissions
    the run fails LOUDLY via x_overflow instead of losing rows."""
    def run_compact(cx):
        yaml = TGEN_YAML.format(
            policy="tpu", seed=3, loss=0.02, clients=6,
            size="100KiB", count=2, stop="8s", extra="retry=500ms")
        yaml = yaml.replace(
            "  outbox_capacity: 256",
            f"  outbox_capacity: 256\n  outbox_compact: {cx}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        return stats, [(h.name, h.trace_checksum, h.packets_sent)
                       for h in c.sim.hosts]

    s_base, sig_base = run_compact(0)       # compaction off
    assert s_base.ok
    s_on, sig_on = run_compact(64)          # on, ample width
    assert s_on.ok
    assert sig_on == sig_base

    s_tiny, _ = run_compact(1)              # far below the server's
    assert not s_tiny.ok                    # per-phase emissions
