"""Columnar host plane (host/plane.py): bit-identity vs the object
build, lazy materialization, bulk DNS parity, and refusal paths.

The contract under test: a columnar build is a REPRESENTATION change
only. Run signatures (per-host trace checksums + counters), checkpoint
fingerprints, and every materialized Host field must be bit-identical
to what the per-host object loop constructs — the fast path may only
change who pays, and when.
"""

import logging
import os

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller, build, load_topology
from shadow_tpu.host import plane as planemod
from shadow_tpu.routing.dns import Dns

TGEN_YAML = """
general:
  stop_time: 4s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
      ]
{faults}
experimental:
  scheduler_policy: {policy}
  event_capacity: 192
  outbox_capacity: 256
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_server
      start_time: 10ms
  client:
    quantity: 6
    network_node_id: 1
    {pcap}processes:
    - path: model:tgen_client
      args: server=server size=100KiB count=2 pause=150ms
      start_time: 100ms
"""

PHOLD_YAML = """
general:
  stop_time: 2s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
      ]
{faults}
experimental:
  scheduler_policy: {policy}
hosts:
  east:
    quantity: 6
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload=3 size=256
      start_time: 50ms
  west:
    quantity: 6
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload=3 size=256
      start_time: 50ms
"""

LINK_FAULTS = """
  faults:
    - {kind: degrade, time: 1000ms, duration: 800ms, source: 0,
       target: 1, latency_multiplier: 3, extra_packet_loss: 0.05}
    - {kind: link_down, time: 2500ms, source: 0, target: 1}
    - {kind: link_up, time: 3000ms, source: 0, target: 1}
"""

HOST_FAULT = """
  faults:
    - {kind: host_crash, time: 1s, host: client2}
    - {kind: host_restart, time: 2s, host: client2}
"""


def _signature(hosts):
    return [(h.name, h.trace_checksum, h.events_executed,
             h.packets_sent, h.packets_dropped, h.packets_delivered)
            for h in hosts]


def _run(yaml, columnar=True):
    """Run one leg; returns (controller, signature). The object leg
    forces the kill-switch; both legs assert they got the build they
    asked for (a vacuous comparison proves nothing)."""
    old = os.environ.pop("SHADOW_TPU_HOST_PLANE", None)
    try:
        if not columnar:
            os.environ["SHADOW_TPU_HOST_PLANE"] = "0"
        c = Controller(load_config_str(yaml))
        c.run()
    finally:
        os.environ.pop("SHADOW_TPU_HOST_PLANE", None)
        if old is not None:
            os.environ["SHADOW_TPU_HOST_PLANE"] = old
    if c.cfg.experimental.scheduler_policy == "tpu" and columnar:
        assert c.sim.plane is not None, "fast path was refused"
    if not columnar:
        assert c.sim.plane is None, "kill-switch ignored"
    return c, _signature(c.sim.hosts)


# ------------------------------------------------- bit-identity legs
@pytest.mark.parametrize("faults", ["", LINK_FAULTS],
                         ids=["nofaults", "linkfaults"])
def test_tgen_columnar_object_serial_identical(faults):
    yaml = TGEN_YAML.format(seed=3, policy="tpu", faults=faults,
                            pcap="")
    col, sig_col = _run(yaml, columnar=True)
    obj, sig_obj = _run(yaml, columnar=False)
    assert sig_col == sig_obj
    # checkpoint fingerprints pin world + app + capacities: the
    # columnar engine must be indistinguishable from the object one
    from shadow_tpu.device import checkpoint
    assert checkpoint._fingerprint(col.runner.engine) == \
        checkpoint._fingerprint(obj.runner.engine)
    _, sig_ser = _run(yaml.replace("scheduler_policy: tpu",
                                   "scheduler_policy: serial"))
    assert sig_col == sig_ser


@pytest.mark.parametrize("faults", ["", LINK_FAULTS],
                         ids=["nofaults", "linkfaults"])
def test_phold_columnar_object_identical(faults):
    yaml = PHOLD_YAML.format(seed=5, policy="tpu", faults=faults)
    col, sig_col = _run(yaml, columnar=True)
    obj, sig_obj = _run(yaml, columnar=False)
    assert sig_col == sig_obj
    from shadow_tpu.device import checkpoint
    assert checkpoint._fingerprint(col.runner.engine) == \
        checkpoint._fingerprint(obj.runner.engine)


# --------------------------------------------- lazy materialization
def test_device_run_materializes_nothing():
    yaml = TGEN_YAML.format(seed=3, policy="tpu", faults="", pcap="")
    old = os.environ.pop("SHADOW_TPU_HOST_PLANE", None)
    try:
        c = Controller(load_config_str(yaml))
        c.run()
    finally:
        if old is not None:
            os.environ["SHADOW_TPU_HOST_PLANE"] = old
    plane = c.sim.plane
    assert plane is not None
    # the whole run — twin mapping, engine build, stats reflection —
    # touched ZERO Host objects
    assert plane.materialized_count == 0
    # reading one host materializes exactly one, with the run's stats
    h = c.sim.hosts[3]
    assert plane.materialized_count == 1
    assert h.events_executed > 0
    assert h.trace_checksum != 0


def test_materialized_host_matches_object_built():
    yaml = TGEN_YAML.format(seed=9, policy="tpu", faults="", pcap="")
    cfg = load_config_str(yaml)
    col = build(cfg)
    assert col.plane is not None
    old = os.environ.get("SHADOW_TPU_HOST_PLANE")
    os.environ["SHADOW_TPU_HOST_PLANE"] = "0"
    try:
        obj = build(cfg)
    finally:
        if old is None:
            del os.environ["SHADOW_TPU_HOST_PLANE"]
        else:
            os.environ["SHADOW_TPU_HOST_PLANE"] = old
    assert obj.plane is None
    for i in range(len(obj.hosts)):
        a, b = col.hosts[i], obj.hosts[i]
        assert (a.name, a.host_id, a.vertex, a.bw_down_bits,
                a.bw_up_bits, a.ip, a.pcap_directory) == \
            (b.name, b.host_id, b.vertex, b.bw_down_bits,
             b.bw_up_bits, b.ip, b.pcap_directory)
        # the EXACT blake2b child seed, not merely an equal stream
        assert a.rng.seed == b.rng.seed
        assert a.address.ip == b.address.ip
        assert type(a.app) is type(b.app)
        assert len(a.respawn) == len(b.respawn) == 1
        assert a.respawn[0][1:] == b.respawn[0][1:]
    # group maps agree (range vs list representations)
    assert {k: list(v) for k, v in col.groups.items()} == obj.groups
    # StartColumns iterates as boot_hosts tuples
    assert list(col.starts) == obj.starts


def test_host_fault_resolves_without_materializing_and_runs_hybrid():
    """A host fault named by generated name resolves through the
    PlaneNameMap at build time; the run lands on the hybrid backend
    (manager-side crash/restart), which materializes hosts — and the
    result bit-matches the object build end to end."""
    yaml = TGEN_YAML.format(seed=3, policy="tpu", faults=HOST_FAULT,
                            pcap="")
    cfg = load_config_str(yaml)
    sim = build(cfg)
    assert sim.plane is not None
    assert sim.plane.materialized_count == 0
    hid = sim.plane.names["client2"]
    assert [(t, h) for t, h, _ in sim.host_faults] == \
        [(1_000_000_000, hid), (2_000_000_000, hid)]
    assert sim.plane.materialized_count == 0
    col, sig_col = _run(yaml, columnar=True)
    obj, sig_obj = _run(yaml, columnar=False)
    assert sig_col == sig_obj
    # both legs fell back to hybrid (host faults are manager events)
    assert col.runner is None and obj.runner is None


def test_pcap_config_stays_columnar_with_warning(tmp_path, caplog):
    yaml = TGEN_YAML.format(
        seed=3, policy="tpu", faults="",
        pcap=f"pcap_directory: {tmp_path}\n    ")
    with caplog.at_level(logging.WARNING):
        c, _ = _run(yaml, columnar=True)
    assert c.sim.plane.any_pcap
    assert any("pcap capture requires a CPU" in r.message
               for r in caplog.records)
    # a materialized client carries the pcap dir; the server does not
    client0 = c.sim.hosts[c.sim.plane.names["client0"]]
    assert client0.pcap_directory == str(tmp_path)
    assert c.sim.hosts[c.sim.plane.names["server"]].pcap_directory \
        is None


# ------------------------------------------------------ refusal paths
def test_managed_process_refused():
    yaml = TGEN_YAML.format(seed=1, policy="tpu", faults="", pcap="")
    yaml = yaml.replace("path: model:tgen_server", "path: /bin/true")
    cfg = load_config_str(yaml)
    reason = planemod.object_build_reason(cfg, load_topology(cfg))
    assert reason is not None and "managed process" in reason
    assert "/bin/true" in reason


def test_cpu_policy_refused_quietly():
    yaml = PHOLD_YAML.format(seed=1, policy="serial", faults="")
    cfg = load_config_str(yaml)
    reason = planemod.object_build_reason(cfg, load_topology(cfg))
    assert reason is not None and "CPU-policy backend" in reason


def test_non_columnar_model_falls_back_loudly(caplog):
    yaml = PHOLD_YAML.format(seed=1, policy="tpu", faults="").replace(
        "path: model:phold", "path: model:tgen_tcp_client").replace(
        "args: msgload=3 size=256", "args: server=east0")
    cfg = load_config_str(yaml)
    with caplog.at_level(logging.WARNING):
        sim = build(cfg)
    assert sim.plane is None
    assert any("[host-plane] falling back" in r.message
               for r in caplog.records)


def test_group_name_collision_refused():
    yaml = TGEN_YAML.format(seed=1, policy="tpu", faults="", pcap="")
    yaml = yaml.replace("  server:", "  client2:", 1).replace(
        "server=server", "server=client2")
    cfg = load_config_str(yaml)
    reason = planemod.object_build_reason(cfg, load_topology(cfg))
    assert reason is not None and "collide" in reason
    # and the object build it falls back to still refuses the
    # ambiguous layout through DNS's duplicate detection
    with pytest.raises(ValueError, match="duplicate host name"):
        build(cfg)


# --------------------------------------------------------- name maps
def test_plane_name_map_edges():
    g1 = planemod.PlaneGroup(name="web", base_id=0, count=20,
                             pcap_directory=None, path="model:phold",
                             args="", start_time=0, stop_time=-1,
                             model="phold", prototype=None)
    g2 = planemod.PlaneGroup(name="db", base_id=20, count=1,
                             pcap_directory=None, path="model:phold",
                             args="", start_time=0, stop_time=-1,
                             model="phold", prototype=None)
    names = planemod.PlaneNameMap([g1, g2])
    assert names.get("web0") == 0
    assert names.get("web19") == 19
    assert names["db"] == 20
    assert names.get("web20") is None      # out of range
    assert names.get("web") is None        # bare multi-host group name
    assert names.get("web01") is None      # generated names: no zeros
    assert names.get("nothere") is None
    assert "web7" in names and "web99" not in names
    with pytest.raises(KeyError):
        names["web99"]


def test_start_columns_sequence_behavior():
    sc = planemod.StartColumns(np.array([10, 20, 30]),
                               np.array([100, -1, 300]))
    assert len(sc) == 3
    assert list(sc) == [(0, 10, 100, 0), (1, 20, -1, 0),
                        (2, 30, 300, 0)]
    assert sc[-1] == (2, 30, 300, 0)
    assert sc[0:2] == [(0, 10, 100, 0), (1, 20, -1, 0)]
    with pytest.raises(IndexError):
        sc[3]
    t0, t1 = sc.as_arrays()
    assert t0.dtype == np.int64 and t1.dtype == np.int64


# ------------------------------------------------------ DNS bulk path
def test_dns_block_matches_scalar_allocation():
    """600 IPs cross the .0/.255 skip boundaries many times; the block
    allocator must draw the exact sequence 600 scalar calls draw."""
    scalar, block = Dns(), Dns()
    want = [scalar.register(i, f"h{i}").ip for i in range(600)]
    got = block.register_block(0, "h", 600)
    assert got.tolist() == want
    for probe in (0, 1, 254, 255, 256, 511, 599):
        name = f"h{probe}"
        a, b = scalar.resolve_name(name), block.resolve_name(name)
        assert (a.host_id, a.name, a.ip) == (b.host_id, b.name, b.ip)
        a, b = scalar.address_of(probe), block.address_of(probe)
        assert (a.host_id, a.name, a.ip) == (b.host_id, b.name, b.ip)
        assert block.resolve_ip(want[probe]).name == name
    assert block.resolve_name("h600") is None
    assert block.resolve_ip(want[0] - 1) is None
    assert block.address_of(600) is None


def test_dns_block_interleaves_with_scalar_and_hosts_file(tmp_path):
    a, b = Dns(), Dns()
    a.register(0, "lone")
    b.register(0, "lone")
    for i in range(5):
        a.register(1 + i, f"web{i}")
    b.register_block(1, "web", 5)
    a.register(6, "tail")
    b.register(6, "tail")
    fa, fb = tmp_path / "a", tmp_path / "b"
    a.write_hosts_file(str(fa))
    b.write_hosts_file(str(fb))
    assert fa.read_text() == fb.read_text()


def test_dns_block_duplicate_detection():
    d = Dns()
    d.register(0, "web3")
    with pytest.raises(ValueError, match="duplicate host name 'web3'"):
        d.register_block(1, "web", 5)
    d2 = Dns()
    d2.register_block(0, "web", 20)
    with pytest.raises(ValueError, match="duplicate host name"):
        d2.register(20, "web5")
    with pytest.raises(ValueError,
                       match="duplicate host group 'web'"):
        d2.register_block(20, "web", 3)
    # nested prefixes that do NOT collide are fine: web1 x3 makes
    # web10..web12, outside web0..web19? no — web10..web12 ARE inside
    # web's range, so this must raise
    with pytest.raises(ValueError, match="duplicate host name"):
        d2.register_block(20, "web1", 3)
    # but a genuinely disjoint nesting passes: web has 5 hosts
    # (web0..web4), so web1's generated web10.. never collide
    d3 = Dns()
    d3.register_block(0, "web", 5)
    d3.register_block(5, "web1", 3)
    assert d3.resolve_name("web10").host_id == 5
