"""Pipelined segment dispatch (experimental.pipeline_depth, PR 11).

The segment pipeline in device/supervise.py keeps up to N dispatch
segments in flight while a strictly-ordered drain performs the
blocking syncs and boundary side effects. Its whole contract is that
overlap is INVISIBLE to the simulation: every depth bit-matches the
serial loop, and every recovery class (capacity overflow, transient
dispatch errors, preemption) discards the speculative window and
replays from the last validated state. This file pins:

* depth sweep bit-identity + pipeline telemetry sanity;
* forced overflow mid-window: the re-plan replays serially and still
  bit-matches the static run;
* a transient dispatch error with speculative segments in flight
  respects the CONSECUTIVE-failure budget (recovered incidents reset
  it; a dead device still exhausts it);
* SIGTERM with a depth-4 window in flight drains to a valid resume
  checkpoint, and the checkpoint round-trips ACROSS depths (save at
  depth 4, load at depth 1 and vice versa — depth is host-side
  orchestration, never part of the checkpoint contract);
* depth 0/1 reproduce the serial loop; the schema gates the knob;
* the autotuner knob registration and plan-adoption round-trip.
"""

import os
import signal

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import supervise

YAML = """
general:
  stop_time: 800ms
  seed: 9
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


def _run(extra=""):
    c = Controller(load_config_str(YAML.format(extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


PIPED = ("  dispatch_segment: 100ms\n"
         "  state_audit: true\n"
         "  pipeline_depth: {depth}")


# ---------------------------------------------------------------------------
# depth sweep: bit-identity + telemetry sanity
# ---------------------------------------------------------------------------

def test_depth_sweep_bitmatches_serial():
    ref_stats, ref_c = _run()
    ref = _sig(ref_stats, ref_c)
    for depth in (2, 4):
        stats, c = _run(PIPED.format(depth=depth))
        assert stats.ok
        assert _sig(stats, c) == ref, f"depth {depth} diverged"
        p = stats.pipeline
        assert p["depth"] == depth
        # 800ms / 100ms segments: the window genuinely filled
        assert p["issued"] == p["drained"] == 8
        assert p["max_in_flight"] >= 2
        assert p["discarded"] == 0
        assert 0.0 <= p["overlap_efficiency"] <= 1.0
        # the sync wall is measured, not the whole advance: issue
        # enqueues must not be counted as blocking waits
        assert 0.0 <= p["sync_wall_s"] <= p["advance_wall_s"]


def test_depth_0_and_1_reproduce_the_serial_loop():
    ref_stats, ref_c = _run("  dispatch_segment: 100ms")
    ref = _sig(ref_stats, ref_c)
    for depth in (0, 1):
        stats, c = _run(f"  dispatch_segment: 100ms\n"
                        f"  pipeline_depth: {depth}")
        assert stats.ok
        assert _sig(stats, c) == ref
        p = stats.pipeline
        assert p["depth"] == 1              # 0 normalizes to serial
        assert p["max_in_flight"] == 1
        # at depth 1 the window is empty whenever the host works:
        # overlap is structurally impossible, and the telemetry must
        # say so rather than flatter the serial loop
        assert p["overlapped_host_s"] == 0.0
        assert p["overlap_efficiency"] == 0.0


# ---------------------------------------------------------------------------
# recovery class 1: capacity overflow mid-window -> re-plan + replay
# ---------------------------------------------------------------------------

def test_forced_overflow_mid_window_replays_and_bitmatches(
        tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    ref_stats, ref_c = _run("  dispatch_segment: 100ms")
    assert ref_stats.ok
    ref = _sig(ref_stats, ref_c)

    # the warm-up slice ends before the phold boots at 10ms, so the
    # plan is sized on an empty slice (floors only) and the first
    # real segment must overflow — with a depth-4 window in flight,
    # so the re-plan discards speculative successors and replays
    stats, c = _run("  dispatch_segment: 100ms\n"
                    "  pipeline_depth: 4\n"
                    "  capacity_plan: auto\n"
                    "  capacity_warmup: 5ms")
    assert stats.ok, "re-plan/retry failed to absorb the overflow"
    assert stats.replans >= 1
    assert _sig(stats, c) == ref
    p = stats.pipeline
    # the overflow was discovered at a drain with speculative
    # segments in flight: the window was discarded and re-issued
    assert p["discarded"] >= 1
    assert p["drained"] >= 8


# ---------------------------------------------------------------------------
# recovery class 2: transient dispatch errors under a deep window
# ---------------------------------------------------------------------------

def test_transient_error_with_inflight_respects_budget(monkeypatch):
    ref_stats, ref_c = _run()
    ref = _sig(ref_stats, ref_c)

    import shadow_tpu.device.engine as eng
    orig = eng.DeviceEngine.run
    calls = {"n": 0}

    def flaky(self, state, stop=None, final_stop=None):
        calls["n"] += 1
        if calls["n"] == 4:     # a mid-run issue, 3 segments already
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        return orig(self, state, stop=stop, final_stop=final_stop)

    monkeypatch.setattr(eng.DeviceEngine, "run", flaky)
    stats, c = _run(PIPED.format(depth=4) +
                    "\n  dispatch_retries: 2"
                    "\n  dispatch_retry_backoff: 0.0")
    assert stats.ok
    assert stats.retries == 1
    assert _sig(stats, c) == ref

    # CONSECUTIVE-failure budget: two hiccups in different segments
    # each recover under dispatch_retries: 1 — a drained-clean
    # segment resets the count even with a deep speculative window
    calls["n"] = 0

    def flaky_twice(self, state, stop=None, final_stop=None):
        calls["n"] += 1
        if calls["n"] in (3, 9):
            raise RuntimeError("UNAVAILABLE: injected hiccup")
        return orig(self, state, stop=stop, final_stop=final_stop)

    monkeypatch.setattr(eng.DeviceEngine, "run", flaky_twice)
    stats2, c2 = _run(PIPED.format(depth=4) +
                      "\n  dispatch_retries: 1"
                      "\n  dispatch_retry_backoff: 0.0")
    assert stats2.ok
    assert stats2.retries == 2
    assert _sig(stats2, c2) == ref

    # a genuinely dead device exhausts the budget: no segment ever
    # drains clean, so the failures stay consecutive and the error
    # surfaces after dispatch_retries replays
    def dead(self, state, stop=None, final_stop=None):
        raise RuntimeError("UNAVAILABLE: device went away")

    monkeypatch.setattr(eng.DeviceEngine, "run", dead)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        _run(PIPED.format(depth=4) +
             "\n  dispatch_retries: 2"
             "\n  dispatch_retry_backoff: 0.0")


# ---------------------------------------------------------------------------
# recovery class 3: preemption drain + cross-depth resume
# ---------------------------------------------------------------------------

def test_preempt_drain_with_inflight_and_cross_depth_resume(
        tmp_path, monkeypatch):
    full_stats, full_c = _run()
    assert full_stats.ok
    ref = _sig(full_stats, full_c)

    # SIGTERM raised synchronously after the third dispatch ISSUE:
    # with depth 4 the window holds speculative segments at that
    # moment, so the drain must complete them through their boundary
    # work before saving the resume checkpoint
    base = str(tmp_path / "ck.npz")
    import shadow_tpu.device.engine as eng
    orig = eng.DeviceEngine.run
    calls = {"n": 0}

    def poking(self, state, stop=None, final_stop=None):
        out = orig(self, state, stop=stop, final_stop=final_stop)
        calls["n"] += 1
        if calls["n"] == 3:
            signal.raise_signal(signal.SIGTERM)
        return out

    monkeypatch.setattr(eng.DeviceEngine, "run", poking)
    pre_stats, _ = _run(
        PIPED.format(depth=4) +
        f"\n  checkpoint_save: {base}"
        f"\n  checkpoint_every: 200ms"
        f"\n  checkpoint_keep: 3")
    assert pre_stats.preempted
    assert pre_stats.resume_path
    assert os.path.exists(pre_stats.resume_path)
    # the drain ran the whole in-flight window through validation:
    # issued work was not thrown away on the signal
    p = pre_stats.pipeline
    assert p["issued"] == p["drained"]
    assert p["discarded"] == 0
    assert pre_stats.events_executed < full_stats.events_executed

    monkeypatch.setattr(eng.DeviceEngine, "run", orig)
    # cross-depth resume: the depth-4 checkpoint loads at depth 1...
    res1_stats, res1_c = _run(f"  checkpoint_load: {base}")
    assert res1_stats.ok and not res1_stats.preempted
    assert _sig(res1_stats, res1_c) == ref
    # ...and at depth 4 with the audit on — depth and audit are host
    # orchestration, never part of the checkpoint contract
    res4_stats, res4_c = _run(PIPED.format(depth=4) +
                              f"\n  checkpoint_load: {base}")
    assert res4_stats.ok
    assert _sig(res4_stats, res4_c) == ref


# ---------------------------------------------------------------------------
# schema gating
# ---------------------------------------------------------------------------

def test_schema_gates_pipeline_depth():
    # >= 2 pipelines DEVICE dispatches: CPU policies are refused
    with pytest.raises(ValueError, match="pipeline_depth"):
        load_config_str(YAML.format(
            extra="  pipeline_depth: 2").replace(
                "scheduler_policy: tpu", "scheduler_policy: serial"))
    # depth 0/1 are the serial loop and valid anywhere
    load_config_str(YAML.format(extra="  pipeline_depth: 1").replace(
        "scheduler_policy: tpu", "scheduler_policy: serial"))
    with pytest.raises(ValueError, match="pipeline_depth"):
        load_config_str(YAML.format(extra="  pipeline_depth: 65"))
    with pytest.raises(ValueError, match="pipeline_depth"):
        load_config_str(YAML.format(extra="  pipeline_depth: -1"))


# ---------------------------------------------------------------------------
# the autotuner knob: registration, candidates, plan round-trip
# ---------------------------------------------------------------------------

def test_tuner_knob_registration_and_plan_roundtrip(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    from shadow_tpu.core.controller import build
    from shadow_tpu.device.runner import device_twin
    from shadow_tpu.tune import plan as planmod
    from shadow_tpu.tune import space

    cfg = load_config_str(YAML.format(extra=""))
    ctx = space.context(cfg, n_shards=1)
    names = [k.name for k in space.applicable(cfg, ctx)]
    assert "pipeline_depth" in names
    knob = space.KNOB_BY_NAME["pipeline_depth"]
    assert not knob.reshapes        # a free runtime knob
    # the default 0 normalizes to 1 in the ladder: advance() runs
    # both as the identical serial loop, so a 0-trial would be a
    # wasted byte-identical duplicate of the 1-trial
    cands = knob.candidates(cfg, ctx)
    assert cands == (1, 2, 4)
    cfg.experimental.pipeline_depth = 4
    assert knob.candidates(cfg, ctx)[0] == 4
    # device policies only: the hybrid judge has no segment window
    cfg_h = load_config_str(YAML.format(extra="").replace(
        "scheduler_policy: tpu", "scheduler_policy: hybrid"))
    ctx_h = space.context(cfg_h, n_shards=1)
    assert "pipeline_depth" not in [
        k.name for k in space.applicable(cfg_h, ctx_h)]

    # assignment validation: strings coerce, junk is refused
    assert space.apply_assignment(
        cfg, {"pipeline_depth": "4"}) == {"pipeline_depth": 4}
    assert cfg.experimental.pipeline_depth == 4
    with pytest.raises(ValueError, match="pipeline_depth"):
        space.apply_assignment(cfg, {"pipeline_depth": -1})
    with pytest.raises(ValueError, match="pipeline_depth"):
        space.apply_assignment(cfg, {"pipeline_depth": 65})

    # plan adoption round-trips the knob and stays bit-identical
    ref_stats, ref_c = _run()
    sim = build(load_config_str(YAML.format(extra="")))
    twin, H = device_twin(sim), len(sim.hosts)
    path = str(tmp_path / "PLAN_pipe.json")
    planmod.save_plan(
        {"format": planmod.FORMAT,
         "workload": {**planmod.workload_stamp(twin, H),
                      "stop_time": 800_000_000, "seed": 9},
         "default": {}, "knobs": {"pipeline_depth": 2},
         "score": {"pkts_per_s": 1.0}}, path)
    stats, c = _run(f"  strategy_plan: {path}")
    assert stats.ok
    assert c.sim.cfg.experimental.pipeline_depth == 2
    assert stats.strategy_plan["knobs"] == {"pipeline_depth": 2}
    assert stats.pipeline["depth"] == 2
    assert _sig(stats, c) == _sig(ref_stats, ref_c)


# ---------------------------------------------------------------------------
# the PipelineWindow ring itself
# ---------------------------------------------------------------------------

def test_pipeline_window_fifo_and_discard():
    w = supervise.PipelineWindow(2)
    assert len(w) == 0 and not w.full
    a = supervise._InFlight(0, 1, "sa", "ra")
    b = supervise._InFlight(1, 2, "sb", "rb")
    w.push(a)
    w.push(b)
    assert w.full
    assert w.pop() is a             # strictly issue order
    assert w.discard() == 1
    assert len(w) == 0
    # depth 0 normalizes to 1 (the serial loop)
    assert supervise.PipelineWindow(0).depth == 1
