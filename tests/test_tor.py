"""Tor model: CPU-vs-device equivalence, determinism, route math.

The reference's flagship workload (README.md:66-69, src/test/tor/,
.github/workflows/run_tor.yml) is Tor network simulation. Our model
twin: clients pull cells through 3-hop onion circuits; relays are
stateless because circuits are pure functions of the client id —
which is what makes the device form one vectorized branch.
"""

import os

import numpy as np
import pytest

from shadow_tpu.config import load_config_str, load_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.models.tor import TorClientApp, TorRelayApp, pick_route

TOR_YAML = """
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss {loss} ]
        edge [ source 0 target 1 latency "40 ms" packet_loss {loss} ]
        edge [ source 1 target 1 latency "20 ms" packet_loss {loss} ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 96
  outbox_capacity: 48
hosts:
  relay:
    quantity: {relays}
    network_node_id: 0
    processes: [{{path: model:tor_relay, start_time: 100ms}}]
  client:
    quantity: {clients}
    network_node_id: 1
    processes:
    - {{path: model:tor_client, args: cells={cells} count=2 pause=500ms{retry}, start_time: 1s}}
"""


def _run(policy, seed=1, loss=0.0, relays=8, clients=16, cells=48,
         stop="20s", retry="", extra=""):
    yaml = TOR_YAML.format(policy=policy, seed=seed, loss=loss,
                           relays=relays, clients=clients, cells=cells,
                           stop=stop, retry=retry)
    if extra:
        yaml = yaml.replace("experimental:", "experimental:\n" + extra)
    c = Controller(load_config_str(yaml))
    stats = c.run()
    return stats, c.sim.hosts


def test_pick_route_distinct():
    rng = np.random.RandomState(0)
    for _ in range(500):
        bits = tuple(int(x) for x in rng.randint(0, 2**32, 3,
                                                 dtype=np.uint32))
        for r in (3, 4, 7, 50):
            g, m, e = pick_route(bits, r)
            assert len({g, m, e}) == 3
            assert all(0 <= x < r for x in (g, m, e))


def test_tor_clients_complete_downloads_cpu():
    stats, hosts = _run("serial")
    clients = [h for h in hosts if isinstance(h.app, TorClientApp)]
    relays = [h for h in hosts if isinstance(h.app, TorRelayApp)]
    assert all(h.app.downloads_done == 2 for h in clients), \
        [h.app.downloads_done for h in clients]
    assert all(h.app.cells_received == 2 * 48 for h in clients)
    assert sum(h.app.cells_relayed for h in relays) > 0
    assert stats.ok


# the strategy stack production TPU auto-selects (judge flush +
# global double-sort merge + one-hot pop): the on-chip tor_large run
# executes exactly this combination on the TOR app — onion trains
# with per-hop survivor masks and relay burst pops — so it is pinned
# against the serial oracle here, not just under the CPU-auto paths
TPU_STACK = ("  judge_placement: flush\n  merge_strategy: global\n"
             "  pop_strategy: onehot")


@pytest.mark.parametrize("loss,retry,extra",
                         [(0.0, "", ""), (0.05, " retry=2s", ""),
                          (0.05, " retry=2s", TPU_STACK)],
                         ids=["lossless", "lossy_retry",
                              "lossy_tpu_default_stack"])
def test_tor_device_matches_serial_oracle(loss, retry, extra):
    s_stats, s_hosts = _run("serial", loss=loss, retry=retry)
    d_stats, d_hosts = _run("tpu", loss=loss, retry=retry, extra=extra)
    assert d_stats.ok
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.packets_sent == d_stats.packets_sent
    assert s_stats.packets_dropped == d_stats.packets_dropped
    for sh, dh in zip(s_hosts, d_hosts):
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_tor_device_deterministic_and_seed_sensitive():
    _, h1 = _run("tpu", seed=11)
    _, h2 = _run("tpu", seed=11)
    _, h3 = _run("tpu", seed=12)
    assert [h.trace_checksum for h in h1] == \
        [h.trace_checksum for h in h2]
    assert [h.trace_checksum for h in h1] != \
        [h.trace_checksum for h in h3]


def test_tor_small_example_loads_and_maps_to_device():
    """examples/tor_small.yaml (BASELINE #4 shape) builds a device twin
    with the right roles; a short-stop run executes events on device."""
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "tor_small.yaml")
    cfg = load_config(path)
    cfg.general.stop_time = 2_000_000_000      # trim for test runtime
    cfg.general.bootstrap_end_time = 500_000_000
    c = Controller(cfg)
    assert c.runner is not None, "tor_small must map to the device twin"
    app = c.runner.app
    assert int(app.roles.sum()) == 200          # clients
    assert len(app.relay_gids) == 50
    stats = c.run()
    assert stats.ok
    assert stats.events_executed > 0
    assert stats.packets_sent > 0


def test_tor_large_config_builds():
    """BASELINE config #5 (56k hosts, tornettools scale ~1.0): the
    full-consensus config parses, attaches, and the device engine
    builds its capacity plan — the run itself needs TPU HBM, so this
    guards the config and the planning path, and a 1/400-scale twin
    of the same shape actually executes."""
    import numpy as np

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config("examples/tor_large.yaml")
    c = Controller(cfg)
    eng = c.runner.engine
    assert eng.config.n_hosts == 56000
    st = eng.init_state(c.sim.starts)
    boots = int((np.asarray(st["ht"]) < (1 << 62)).sum())
    assert boots == 56000                  # every host has a boot event
    del st, c, eng                         # ~1 GB back before the run

    # downscale 1/400 with the same role mix and run a short slice
    # (the CPU jax backend compiles E=416 programs slowly; this keeps
    # the shape-faithful execution check affordable in CI)
    cfg2 = load_config("examples/tor_large.yaml")
    for h in cfg2.hosts:
        h.quantity = max(1, h.quantity // 400)
        for p in h.processes:
            if isinstance(p.args, str) and "cells=" in p.args:
                p.args = p.args.replace("cells=256", "cells=48")
    cfg2.general.stop_time = 8_000_000_000
    cfg2.experimental.event_capacity = 288
    c2 = Controller(cfg2)
    stats = c2.run()
    assert stats.ok
    assert stats.packets_delivered > 500


def test_tor_heterogeneous_client_args_on_device():
    """count/pause/retry vary per client group (the tornettools
    shape): the device twin's per-host arg arrays bit-match the
    serial oracle; only `cells` must stay uniform."""
    extra = """  client_slow:
    quantity: 8
    network_node_id: 0
    processes:
    - {path: model:tor_client, args: cells=48 count=1 pause=2s retry=900ms, start_time: 2s}
"""
    outs = {}
    for policy in ("serial", "tpu"):
        yaml = TOR_YAML.format(
            policy=policy, seed=5, loss=0.02, relays=8, clients=8,
            cells=48, stop="20s", retry=" retry=400ms") + extra
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, policy
        outs[policy] = ([h.trace_checksum for h in c.sim.hosts],
                        stats.packets_sent, stats.packets_dropped)
    assert outs["serial"] == outs["tpu"]
