from shadow_tpu import simtime


def test_constants():
    assert simtime.SIMTIME_ONE_SECOND == 10**9
    assert simtime.SIMTIME_ONE_MILLISECOND == 10**6
    assert simtime.SIMTIME_ONE_MINUTE == 60 * 10**9
    assert simtime.CONFIG_MTU == 1500
    assert simtime.CONFIG_TCP_MAX_SEGMENT_SIZE == 1460


def test_conversions():
    assert simtime.from_seconds(1.5) == 1_500_000_000
    assert simtime.from_millis(10) == 10_000_000
    assert simtime.to_seconds(simtime.SIMTIME_ONE_HOUR) == 3600.0


def test_emulated_offset():
    # Sim time 0 is 2000-01-01 UTC.
    assert simtime.to_emulated(0) == 946_684_800 * 10**9
    assert simtime.from_emulated(simtime.to_emulated(123)) == 123


def test_format():
    assert simtime.format_time(0) == "00:00:00.000000000"
    t = 2 * simtime.SIMTIME_ONE_HOUR + 3 * simtime.SIMTIME_ONE_MINUTE + 7
    assert simtime.format_time(t) == "02:03:00.000000007"
    assert simtime.format_time(simtime.SIMTIME_INVALID) == "n/a"
