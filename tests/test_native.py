"""Python-side tests of the native runtime (shmem arena + IPC)."""

import multiprocessing as mp
import os
import shutil
import subprocess

import pytest

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def test_cpp_unit_tests_pass():
    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    r = subprocess.run(["make", "-C", native_dir, "test"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL NATIVE TESTS PASSED" in r.stdout


def test_arena_roundtrip():
    from shadow_tpu import native
    name = f"/shadowtpu_shm_{os.getpid()}_t1"
    a = native.ShmArena(name, 1 << 20)
    try:
        p1 = a.alloc(1000)
        p2 = a.alloc(5000)
        assert a.allocated > 0
        off = a.offset_of(p2)
        assert a.at_offset(off) == p2
        a.free(p1)
        a.free(p2)
        assert a.allocated == 0
        with pytest.raises(MemoryError):
            a.alloc(1 << 30)
    finally:
        a.unlink()
        a.close()


def _plugin_side(name: str, off: int) -> None:
    from shadow_tpu import native
    arena = native.ShmArena(name, create=False)
    ch = native.IpcChannel(arena, ptr=arena.at_offset(off))
    m = ch.recv_from_simulator()
    assert m.kind == native.IPC_START
    for i in range(100):
        req = native.IpcMessage(kind=native.IPC_SYSCALL, number=39)
        req.args[0] = i
        ch.send_to_simulator(req)
        r = ch.recv_from_simulator()
        assert r.kind == native.IPC_SYSCALL_DONE
        assert r.number == i * 3
    ch.mark_plugin_exited()


def test_cross_process_ipc():
    from shadow_tpu import native
    name = f"/shadowtpu_shm_{os.getpid()}_t2"
    arena = native.ShmArena(name, 1 << 20)
    try:
        ch = native.IpcChannel(arena)
        proc = mp.get_context("spawn").Process(
            target=_plugin_side, args=(name, ch.offset))
        proc.start()
        ch.send_to_plugin(native.IpcMessage(kind=native.IPC_START))
        handled = 0
        while True:
            m = ch.recv_from_plugin()
            if m is None:
                break
            assert m.kind == native.IPC_SYSCALL
            resp = native.IpcMessage(kind=native.IPC_SYSCALL_DONE,
                                     number=int(m.args[0]) * 3)
            ch.send_to_plugin(resp)
            handled += 1
        proc.join(timeout=30)
        assert proc.exitcode == 0
        assert handled == 100
    finally:
        arena.unlink()
        arena.close()
