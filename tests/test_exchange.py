"""Cross-shard exchange: variant determinism, occupancy-driven
sizing, and LOUD overflow attribution.

The exchange contract this file pins (docs/exchange.md):

* traces are bit-identical across exchange variants (all_to_all /
  all_gather / two_phase / auto) and match the CPU serial oracle;
* an undersized exchange capacity attributes every lost row to the
  SENDING host — including across shards on the two_phase schedule,
  where the loss happens at an intermediate — and fails the run
  loudly (stats.ok False), never silently;
* the planner sizes the per-pair CAP from the measured occ_x
  high-water marks (measured * HEADROOM + SLACK), far below the
  engine's blind 4x auto padding on sparse workloads.

Tests run on the conftest's 8 virtual CPU devices, so every
multi-shard path (ppermute schedules included) executes for real.
"""

import math

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import capacity

# 16 hosts over the 8-device mesh -> H_loc = 2: gids (2s, 2s+1) share
# shard s, so two clients on one shard can overload one shard pair.
# Order matters: yaml declaration order IS gid order.
XCHG_YAML = """
general:
  stop_time: 2s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "5 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 48
  exchange_in_capacity: 48
{extra}
hosts:
{hosts}
"""

CLIENT = """  {name}:
    quantity: {q}
    network_node_id: 1
    processes:
    - path: model:tgen_client
      args: server=srv size=1KiB count=1 pause=500ms retry=10s
      start_time: 100ms
"""
SERVER = """  srv:
    network_node_id: 0
    processes: [{path: model:tgen_server, start_time: 50ms}]
"""
FILLER = """  {name}:
    quantity: {q}
    network_node_id: 0
"""


def _hosts(lead_fillers: int, clients: int, tail_fillers: int) -> str:
    out = ""
    if lead_fillers:
        out += FILLER.format(name="pad_a", q=lead_fillers)
    out += CLIENT.format(name="cli", q=clients)
    out += SERVER
    if tail_fillers:
        out += FILLER.format(name="pad_b", q=tail_fillers)
    return out


def _run(policy: str, hosts: str, extra: str = ""):
    cfg = load_config_str(XCHG_YAML.format(policy=policy, extra=extra,
                                           hosts=hosts))
    c = Controller(cfg)
    stats = c.run()
    return stats, c


def _sig(c):
    return [(h.name, h.trace_checksum, h.events_executed,
             h.packets_sent, h.packets_delivered) for h in c.sim.hosts]


# --------------------------------------------------------------------
# variant determinism: every exchange schedule, bit-identical to the
# serial oracle on the 8-shard mesh
# --------------------------------------------------------------------
def test_exchange_variants_bit_identical_to_serial_oracle():
    hosts = _hosts(0, 2, 13)          # clients gid 0-1, server gid 2
    _, cs = _run("serial", hosts)
    want = _sig(cs)
    for variant in ("all_to_all", "all_gather", "two_phase"):
        stats, c = _run("tpu", hosts,
                        extra=f"  exchange: {variant}\n")
        assert stats.ok, variant
        assert c.runner.engine.config.exchange == variant
        assert _sig(c) == want, f"{variant} diverged from serial"
        eff = c.runner.engine.effective
        assert eff["exchange"] == variant
        if variant != "all_gather":
            assert eff["ICI_rows_per_flush"] > 0


def test_exchange_auto_without_plan_falls_back_to_all_to_all():
    hosts = _hosts(0, 2, 13)
    stats, c = _run("tpu", hosts, extra="  exchange: auto\n")
    assert stats.ok
    assert c.runner.engine.config.exchange == "all_to_all"


def test_exchange_auto_resolves_from_measured_record(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    hosts = _hosts(0, 2, 13)
    _, cs = _run("serial", hosts)
    stats, c = _run("tpu", hosts,
                    extra="  exchange: auto\n"
                          "  capacity_plan: auto\n"
                          "  capacity_warmup: 500ms\n")
    assert stats.ok
    rec = c.runner.occ_record
    info = rec["exchange_auto"]
    assert info["chosen"] == c.runner.engine.config.exchange
    assert info["chosen"] in ("all_to_all", "all_gather", "two_phase")
    assert set(info["estimates"]) == {"all_to_all", "all_gather",
                                      "two_phase"}
    # the planned caps came from the occ_x pair matrix, and the trace
    # still matches the oracle under the chosen schedule
    assert _sig(c) == _sig(cs)
    assert "exchange_pairs" in rec["measured"]


# --------------------------------------------------------------------
# loud overflow attribution (the sending host, both merge paths)
# --------------------------------------------------------------------
@pytest.mark.parametrize("merge", ["window", "global"])
def test_x_overflow_attributed_to_sending_host(merge):
    """Two clients on shard 0 burst one REQ each at the same window
    toward the server on shard 1; exchange_capacity=1 holds only the
    first (lower okey = lower gid) row. The second row's loss must
    land on ITS sender (gid 1) exactly, and the run must fail
    loudly."""
    hosts = _hosts(0, 2, 13)          # clients gid 0-1, srv gid 2
    stats, c = _run(
        "tpu", hosts,
        extra=("  exchange: all_to_all\n"
               "  exchange_capacity: 1\n"
               f"  merge_strategy: {merge}\n"))
    assert not stats.ok               # LOUD failure, never silent
    xov = np.asarray(c.runner.final_state["x_overflow"])
    assert xov[1] >= 1, xov           # the overflowing sender
    assert xov[0] == 0 and (xov[2:] == 0).all(), xov
    assert stats.packets_delivered < 4  # the lost REQ cost traffic


@pytest.mark.parametrize("merge", ["window", "global"])
def test_two_phase_overflow_attributed_across_shards(merge):
    """two_phase phase-2 loss happens at the INTERMEDIATE shard, not
    the sender's: clients on shard 1 (group 0, rank 1) reach the
    server on shard 2 (group 1, rank 0) via shard 0, where
    exchange_capacity2=1 drops the second row. The count must still
    land on the true sender (gid 3, on shard 1) via the psum'd
    histogram."""
    hosts = _hosts(2, 2, 11)          # clients gid 2-3, srv gid 4
    stats, c = _run(
        "tpu", hosts,
        extra=("  exchange: two_phase\n"
               "  exchange_capacity2: 1\n"
               f"  merge_strategy: {merge}\n"))
    assert not stats.ok
    xov = np.asarray(c.runner.final_state["x_overflow"])
    assert xov[3] >= 1, xov           # the overflowing sender
    assert (np.delete(xov, 3) == 0).all(), xov


def test_two_phase_phase1_overflow_attributed_locally():
    """Phase-1 loss (exchange_capacity=1 on an intra-group pair)
    never leaves the sender's shard — straight local attribution,
    same as the direct all_to_all pack."""
    hosts = _hosts(0, 2, 13)          # clients gid 0-1 -> srv gid 2
    stats, c = _run(
        "tpu", hosts,
        extra=("  exchange: two_phase\n"
               "  exchange_capacity: 1\n"))
    assert not stats.ok
    xov = np.asarray(c.runner.final_state["x_overflow"])
    assert xov[1] >= 1, xov
    assert xov[0] == 0 and (xov[2:] == 0).all(), xov


# --------------------------------------------------------------------
# degenerate meshes
# --------------------------------------------------------------------
def test_two_phase_on_prime_shard_count_matches_all_to_all():
    """group_split(3) = (1, 3): phase 1 is empty and phase 2 is the
    direct exchange — correct, just profitless (auto never picks
    it)."""
    from shadow_tpu._jax import jax
    from jax.sharding import Mesh
    from shadow_tpu import simtime
    from shadow_tpu.device.apps import PholdDevice
    from shadow_tpu.device.engine import DeviceEngine, EngineConfig
    from shadow_tpu.topology.graph import Topology

    top = Topology.builtin_1_gbit_switch()
    H = 6
    hv = np.zeros(H, np.int32)
    starts = [(h, simtime.from_millis(1), -1) for h in range(H)]
    mesh = Mesh(np.array(jax.devices()[:3]), ("hosts",))

    def run(exchange):
        eng = DeviceEngine(
            EngineConfig(n_hosts=H, event_capacity=16,
                         outbox_capacity=8,
                         lookahead=top.min_latency_ns,
                         stop_time=simtime.from_millis(120),
                         seed=2, exchange=exchange),
            PholdDevice(n_hosts_total=H, msgload=2, size=64),
            host_vertex=hv, latency_ns=top.latency_ns,
            reliability=top.reliability, mesh=mesh)
        st, _ = eng.run(eng.init_state(starts))
        return {k: np.asarray(st[k])
                for k in ("chk", "n_exec", "x_overflow")}

    a, b = run("all_to_all"), run("two_phase")
    assert (b["x_overflow"] == 0).all()
    assert (a["chk"] == b["chk"]).all()
    assert (a["n_exec"] == b["n_exec"]).all()


def test_engine_rejects_auto_exchange():
    from shadow_tpu import simtime
    from shadow_tpu.device.apps import PholdDevice
    from shadow_tpu.device.engine import DeviceEngine, EngineConfig
    from shadow_tpu.topology.graph import Topology

    top = Topology.builtin_1_gbit_switch()
    with pytest.raises(ValueError, match="auto"):
        DeviceEngine(
            EngineConfig(n_hosts=4, exchange="auto",
                         lookahead=top.min_latency_ns,
                         stop_time=simtime.from_millis(10)),
            PholdDevice(n_hosts_total=4, msgload=1, size=64),
            host_vertex=np.zeros(4, np.int32),
            latency_ns=top.latency_ns,
            reliability=top.reliability)


# --------------------------------------------------------------------
# planner math (no device work)
# --------------------------------------------------------------------
def _record(pairs, n_hosts=10000, eff=None):
    pairs = np.asarray(pairs)
    m = {
        "heap_rows_max": 30, "outbox_rows_max": 6,
        "arrivals_per_flush_max": 32,
        "exchange_rows_max": int(pairs.max(initial=0)),
        "exchange_pairs": pairs.tolist(),
        "pop_trips_max": 6, "phases": 100,
        "overflow": 0, "x_overflow": 0,
    }
    return {"format": capacity.FORMAT, "source": "test",
            "workload": {"app": "TgenDevice", "n_hosts": n_hosts},
            "measured": m, "effective": eff or {}}


def test_group_split():
    assert capacity.group_split(4) == (2, 2)
    assert capacity.group_split(8) == (2, 4)
    assert capacity.group_split(16) == (4, 4)
    assert capacity.group_split(12) == (3, 4)
    assert capacity.group_split(7) == (1, 7)
    assert capacity.group_split(1) == (1, 1)


def test_two_phase_caps_are_pair_sums():
    # S=4, g=2: shard s=(a,b); CAP1 covers max over (s, rank) of the
    # per-group sum, CAP2 the max group-total forward
    pairs = np.zeros((4, 4), np.int64)
    pairs[0, 1] = 5      # intra-group (0,0)->(0,1): rank-1 sum = 5
    pairs[0, 3] = 7      # cross (0,0)->(1,1): rank-1 sum 5+7 = 12
    pairs[1, 2] = 4      # cross (0,1)->(1,0)
    cap1, cap2 = capacity.two_phase_caps(pairs, headroom=1.0)
    # pad(x) at headroom 1.0 = x + SLACK
    assert cap1 == max(8, 12 + capacity.SLACK)
    # forwards: group 0 -> group 1 at rank 1: rows from (0,0)+(0,1)
    # destined (1,1) = 7; at rank 0: destined (1,0) = 4
    assert cap2 == max(8, 7 + capacity.SLACK)


def test_plan_sizes_cap_from_occ_x_not_blind_headroom():
    """The acceptance shape of the 10k rung: per-pair CAP tracks the
    measured high-water mark (measured * HEADROOM + SLACK), and the
    engine's blind 4x auto-pack would ship >= 2x more rows."""
    S, H = 8, 10000
    pairs = np.full((S, S), 40, np.int64)   # sparse, balanced-ish
    np.fill_diagonal(pairs, 0)
    rec = _record(pairs, n_hosts=H)
    planned = capacity.plan(rec, per_iter=9, n_shards=S)
    measured = int(pairs.max())
    assert planned["exchange_capacity"] <= \
        math.ceil(measured * capacity.HEADROOM) + capacity.SLACK
    # the engine's 4x auto CAP at these shapes (H_loc * OB rows) —
    # the ONE shared definition (capacity.dense_auto_cap)
    auto_cap = capacity.dense_auto_cap(
        H // S, planned["outbox_capacity"],
        planned["event_capacity"], S)
    assert auto_cap >= 2 * planned["exchange_capacity"], \
        (auto_cap, planned)


def test_plan_two_phase_gets_both_caps():
    S = 8
    pairs = np.full((S, S), 10, np.int64)
    np.fill_diagonal(pairs, 0)
    rec = _record(pairs)
    p = capacity.plan(rec, per_iter=9, n_shards=S,
                      exchange="two_phase")
    assert p["exchange_capacity"] > 0
    assert p["exchange_capacity2"] > 0
    g, ng = capacity.group_split(S)
    c1, c2 = capacity.two_phase_caps(pairs)
    assert p["exchange_capacity"] == c1
    assert p["exchange_capacity2"] == c2
    # all_gather needs no CAP at all
    pg = capacity.plan(rec, per_iter=9, n_shards=S,
                       exchange="all_gather")
    assert pg["exchange_capacity"] == 0
    assert pg["exchange_capacity2"] == 0


def test_choose_exchange_prefers_two_phase_on_skewed_sparse():
    """One hot pair forces the direct CAP to its size for all
    S*(S-1) buffers; the hierarchical schedule pays it on 1 + (ng-1)
    peers only."""
    S = 8
    pairs = np.zeros((S, S), np.int64)
    pairs[1, 6] = 200                  # single hot pair, cross-group
    rec = _record(pairs)
    choice, info = capacity.choose_exchange(rec, S, per_iter=9)
    est = info["estimates"]
    assert est["two_phase"] < est["all_to_all"]
    assert choice == "two_phase"


def test_choose_exchange_balanced_dense_stays_direct():
    S = 4
    pairs = np.full((S, S), 50, np.int64)
    np.fill_diagonal(pairs, 0)
    rec = _record(pairs)
    choice, _ = capacity.choose_exchange(rec, S, per_iter=9)
    assert choice == "all_to_all"


def test_choose_exchange_single_shard_noop():
    rec = _record(np.zeros((1, 1), np.int64), n_hosts=8)
    choice, info = capacity.choose_exchange(rec, 1, per_iter=9)
    assert choice == "all_to_all"
    assert info["estimates"]["all_to_all"] == 0


def test_pair_matrix_fallback_for_scalar_records():
    """Records written before the pair matrix existed (or measured on
    another shard count) fall back to the scalar per-pair max — a
    safe upper bound."""
    m = {"exchange_rows_max": 9}
    pm = capacity.pair_matrix(m, 4)
    assert pm.shape == (4, 4)
    assert (np.diag(pm) == 0).all()
    assert (pm + np.eye(4, dtype=np.int64) * 9 == 9).all()


def test_merged_measured_merges_pair_matrices_elementwise():
    rec = _record(np.array([[0, 3], [1, 0]]), n_hosts=4)
    rec["final_measured"] = {
        "exchange_rows_max": 5,
        "exchange_pairs": [[0, 1], [5, 0]],
    }
    m = capacity.merged_measured(rec)
    assert m["exchange_rows_max"] == 5
    assert m["exchange_pairs"] == [[0, 3], [5, 0]]


def test_widen_doubles_phase2_cap_only_when_live():
    eff = {"E": 32, "IN": 32, "CAP": 16, "CAP2": 24, "CX": 0,
           "OB": 32}
    out = capacity.widen({}, ("exchange_capacity",
                              "exchange_capacity2"), eff)
    assert out["exchange_capacity"] == 32
    assert out["exchange_capacity2"] == 48
    eff2 = dict(eff, CAP2=0)
    out2 = capacity.widen({}, ("exchange_capacity2",), eff2)
    assert "exchange_capacity2" not in out2
