from shadow_tpu.config import load_config_str

YAML = """
general:
  stop_time: 10s
  seed: 42
  parallelism: 4
  bootstrap_end_time: 2s

network:
  graph:
    type: gml
    inline: |
      graph [ node [ id 0 ] ]
  use_shortest_path: false

experimental:
  scheduler_policy: tpu
  runahead: 5 ms
  event_capacity: 128

hosts:
  server:
    network_node_id: 0
    bandwidth_down: 100 Mbit
    bandwidth_up: 50 Mbit
    processes:
    - path: /bin/server
      args: "--listen 80"
      start_time: 1s
  client:
    quantity: 10
    processes:
    - path: /bin/client
      args: ["--connect", "server"]
      start_time: 2s
      stop_time: 9s
"""


def test_parse_full():
    cfg = load_config_str(YAML)
    assert cfg.general.stop_time == 10 * 10**9
    assert cfg.general.seed == 42
    assert cfg.general.parallelism == 4
    assert cfg.general.bootstrap_end_time == 2 * 10**9
    assert cfg.network.graph_type == "gml"
    assert "node" in cfg.network.graph_inline
    assert not cfg.network.use_shortest_path
    assert cfg.experimental.scheduler_policy == "tpu"
    assert cfg.experimental.runahead == 5_000_000
    assert cfg.experimental.event_capacity == 128
    assert cfg.total_hosts() == 11
    server = next(h for h in cfg.hosts if h.name == "server")
    assert server.bandwidth_down == 100_000_000
    assert server.bandwidth_up == 50_000_000
    assert server.processes[0].start_time == 10**9
    client = next(h for h in cfg.hosts if h.name == "client")
    assert client.quantity == 10
    assert client.processes[0].stop_time == 9 * 10**9


def test_overrides():
    cfg = load_config_str(YAML, overrides=["general.stop_time=20s",
                                           "general.seed=7"])
    assert cfg.general.stop_time == 20 * 10**9
    assert cfg.general.seed == 7


def test_defaults():
    cfg = load_config_str("general: {stop_time: 1}")
    assert cfg.network.graph_type == "1_gbit_switch"
    assert cfg.experimental.router_queue == "codel"
    assert cfg.experimental.exchange == "all_to_all"
    assert cfg.hosts == []
