"""Address-space tracking: the MemoryManager map side (ref
memory_manager/mod.rs + proc_maps.rs + interval_map.rs)."""

import os

from shadow_tpu.host.memmap import (
    IntervalMap,
    Mapping,
    ProcessMaps,
    parse_proc_maps,
)


def test_interval_map_add_clips_overlaps():
    m = IntervalMap()
    m.add(Mapping(0x1000, 0x5000, "rw-p"))
    m.add(Mapping(0x2000, 0x3000, "r--p"))     # MAP_FIXED in the middle
    regions = list(m)
    assert [(r.start, r.end, r.perms) for r in regions] == [
        (0x1000, 0x2000, "rw-p"),
        (0x2000, 0x3000, "r--p"),
        (0x3000, 0x5000, "rw-p"),
    ]
    # file offsets advance through the split
    assert regions[2].offset == 0x2000


def test_interval_map_remove_splits():
    m = IntervalMap()
    m.add(Mapping(0x1000, 0x5000, "rw-p"))
    m.remove(0x2000, 0x3000)                   # munmap a hole
    assert [(r.start, r.end) for r in m] == [
        (0x1000, 0x2000), (0x3000, 0x5000)]
    assert m.find(0x2800) is None
    assert m.find(0x1800).end == 0x2000
    assert not m.covered(0x1800, 0x3800)
    assert m.covered(0x3000, 0x5000)


def test_interval_map_protect():
    m = IntervalMap()
    m.add(Mapping(0x1000, 0x4000, "rw-p"))
    m.protect(0x2000, 0x3000, "r--p")
    assert [(r.start, r.perms) for r in m] == [
        (0x1000, "rw-p"), (0x2000, "r--p"), (0x3000, "rw-p")]


def test_parse_proc_maps_own_process():
    with open(f"/proc/{os.getpid()}/maps") as f:
        regions = parse_proc_maps(f.read())
    assert regions
    stacks = [r for r in regions if r.path == "[stack]"]
    assert stacks and stacks[0].readable
    # every parsed row is well-formed
    for r in regions:
        assert r.end > r.start
        assert len(r.perms) >= 4


def test_process_maps_queries_self():
    pm = ProcessMaps(os.getpid())
    assert pm.refresh()
    r = pm.region_of(id(object()))             # a live heap object
    assert r is not None and r.readable
    # a wild address far above any mapping is not readable
    assert not pm.readable(1 << 46, 64)
    data = b"shadow-tpu memmap test"
    buf = bytearray(data)
    import ctypes
    addr = ctypes.addressof((ctypes.c_char * len(buf)).from_buffer(buf))
    assert pm.readable(addr, len(buf))
    assert pm.writable(addr, len(buf))


def test_process_maps_live_updates():
    pm = ProcessMaps(os.getpid())
    pm.refresh()
    # ptrace-backend style live updates
    pm.on_mmap(0x7000_0000_0000, 0x2000, 3)    # rw
    assert pm.map.find(0x7000_0000_1000).writable
    pm.on_mprotect(0x7000_0000_0000, 0x1000, 1)
    assert not pm.map.find(0x7000_0000_0800).writable
    assert pm.map.find(0x7000_0000_1800).writable
    pm.on_munmap(0x7000_0000_0000, 0x2000)
    assert pm.map.find(0x7000_0000_0800) is None
    # brk growth and shrink (fresh tracker: brk base comes from the
    # first observed call, like a just-spawned plugin)
    pb = ProcessMaps(os.getpid())
    pb.on_brk(0x5555_0000_0000)
    pb.on_brk(0x5555_0000_8000)
    assert pb.map.find(0x5555_0000_4000).path == "[heap]"
    pb.on_brk(0x5555_0000_2000)
    assert pb.map.find(0x5555_0000_4000) is None
