import numpy as np

from shadow_tpu.core.event import Event, EventKey
from shadow_tpu.utils.pqueue import PriorityQueue
from shadow_tpu.utils.rng import (
    PURPOSE_PACKET_DROP,
    SeededRandom,
    base_key,
    uniform01,
)


def test_event_total_order():
    # (time, dst, src, seq) lexicographic — mirrors reference event.c:109-152.
    a = Event(time=5, dst_host=1, src_host=0, seq=0)
    b = Event(time=5, dst_host=1, src_host=0, seq=1)
    c = Event(time=5, dst_host=2, src_host=0, seq=0)
    d = Event(time=4, dst_host=9, src_host=9, seq=9)
    keys = sorted([a.key, b.key, c.key, d.key])
    assert keys == [d.key, a.key, b.key, c.key]
    assert EventKey(5, 1, 0, 0) < EventKey(5, 1, 1, 0)


def test_pqueue_deterministic_order():
    q = PriorityQueue()
    evs = [Event(time=t, dst_host=d, src_host=s, seq=i)
           for i, (t, d, s) in enumerate([(3, 0, 0), (1, 2, 1), (1, 1, 2),
                                          (2, 0, 0), (1, 1, 0)])]
    for e in evs:
        q.push(e.key, e)
    popped = []
    while q:
        popped.append(q.pop()[1])
    times = [e.time for e in popped]
    assert times == sorted(times)
    # ties broken by dst then src
    assert [e.dst_host for e in popped[:3]] == [1, 1, 2]
    assert [e.src_host for e in popped[:2]] == [0, 2]


def test_seeded_random_hierarchy():
    r1 = SeededRandom(42)
    r2 = SeededRandom(42)
    assert r1.child("manager").child("host0").seed == \
        r2.child("manager").child("host0").seed
    assert r1.child("host0").seed != r1.child("host1").seed
    a = r1.child("x").np_rng().random(5)
    b = r2.child("x").np_rng().random(5)
    np.testing.assert_array_equal(a, b)


def test_counter_rng_stable():
    k = base_key(7)
    u1 = uniform01(k, PURPOSE_PACKET_DROP, 3, 100)
    u2 = uniform01(k, PURPOSE_PACKET_DROP, 3, 100)
    u3 = uniform01(k, PURPOSE_PACKET_DROP, 3, 101)
    assert float(u1) == float(u2)
    assert float(u1) != float(u3)
    assert 0.0 <= float(u1) < 1.0
