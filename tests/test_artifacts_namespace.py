"""Per-campaign artifact namespacing (experimental.artifacts_dir).

The collision this guards against: two campaigns of the SAME
workload shape produce identically-named records — the OCC record
path is a deterministic function of (app, n_hosts, fingerprint), and
a METRICS summary's name is ``METRICS_<policy>_<n_hosts>.json`` — so
under a shared artifacts directory the second campaign silently
clobbers the first's records. ``artifacts_dir`` is the one seam all
record writers (OCC via capacity.record_path, METRICS/TRACE via
resolve_tracer) route through, and the campaign server points it at
``campaigns/<cid>/artifacts`` per tenant.
"""

import os

from shadow_tpu.config.loader import load_config_str
from shadow_tpu.device import capacity
from shadow_tpu.obs.trace import resolve_tracer

YAML = """
general:
  stop_time: 200ms
  seed: 9
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


class _FakeApp:
    pass


class _FakeEngine:
    class config:
        n_hosts = 6

    app = _FakeApp()


def test_occ_record_path_collides_without_a_directory_seam(
        monkeypatch, tmp_path):
    # the regression: two tenants, one shared directory -> ONE path.
    # This is the documented shared-dir behavior artifacts_dir exists
    # to avoid, pinned here so a refactor cannot quietly change the
    # canonical naming and hide the hazard.
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path / "shared"))
    eng = _FakeEngine()
    assert capacity.record_path(eng) == capacity.record_path(eng)

    # the fix: an explicit directory wins over the env/shared default,
    # so per-campaign dirs yield disjoint paths for the same workload
    a = capacity.record_path(eng, directory=str(tmp_path / "c0000"))
    b = capacity.record_path(eng, directory=str(tmp_path / "c0001"))
    assert a != b
    assert os.path.basename(a) == os.path.basename(b)
    assert a.startswith(str(tmp_path / "c0000"))


def test_resolve_tracer_routes_records_into_artifacts_dir(tmp_path):
    cfg = load_config_str(YAML.format(
        extra=f"  artifacts_dir: {tmp_path / 'c0000' / 'artifacts'}"))
    tr = resolve_tracer(cfg, n_hosts=6)
    # summary-mode tracers normally write METRICS only when telemetry
    # is on; an artifacts_dir alone must also direct (and enable) the
    # record — the server relies on this for per-tenant METRICS
    assert tr.directory == str(tmp_path / "c0000" / "artifacts")
    tr.finalize()
    files = os.listdir(tmp_path / "c0000" / "artifacts")
    assert any(n.startswith("METRICS_") for n in files)


def test_telemetry_path_still_wins_over_artifacts_dir(tmp_path):
    cfg = load_config_str(YAML.format(
        extra=("  telemetry: summary\n"
               f"  telemetry_path: {tmp_path / 'explicit'}\n"
               f"  artifacts_dir: {tmp_path / 'campaign'}")))
    tr = resolve_tracer(cfg, n_hosts=6)
    # an operator's explicit telemetry_path is a deliberate choice;
    # artifacts_dir is the namespacing default underneath it
    assert tr.directory == str(tmp_path / "explicit")


def test_schema_accepts_and_validates_artifacts_dir():
    cfg = load_config_str(YAML.format(extra="  artifacts_dir: /x/y"))
    assert cfg.experimental.artifacts_dir == "/x/y"
    import pytest
    with pytest.raises(ValueError, match="artifacts_dir"):
        load_config_str(YAML.format(extra="  artifacts_dir: [1]"))
