"""Persistent AOT compile cache (device/aotcache.py).

The subsystem's contract, pinned:
* key sensitivity — every program-shaping input (workload, capacity
  knobs, exchange variant, fault epochs, audit flag, engine code)
  flips the cache key, so a stale entry can never load for the wrong
  trace;
* a cache-hit run is bit-identical to the fresh-compile run that
  wrote the entry;
* a corrupted/truncated entry degrades to a loud recompile (and the
  bad entry is atomically overwritten), never to a wrong trace or a
  crash;
* the cache is bounded: LRU eviction under a size cap;
* two processes racing onto one entry both land complete files
  (atomic tmp+rename — the loser's replace just lands second).
"""

import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.device import aotcache
from shadow_tpu.device.apps import PholdDevice
from shadow_tpu.device.engine import DeviceEngine, EngineConfig

YAML = """
general:
  stop_time: 600ms
  seed: 11
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
  event_capacity: 48
  compile_cache: {cache}
{extra}
hosts:
  left:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
  right:
    quantity: 3
    processes:
    - {{path: model:phold, args: msgload=2, start_time: 10ms}}
"""


def _run(cache_dir, extra=""):
    c = Controller(load_config_str(
        YAML.format(cache=cache_dir, extra=extra)))
    stats = c.run()
    return stats, c


def _sig(stats, c):
    return (stats.events_executed, stats.packets_sent,
            stats.packets_dropped, stats.packets_delivered,
            [(h.name, h.trace_checksum) for h in c.sim.hosts])


def _entries(cache_dir):
    return sorted(p for p in os.listdir(cache_dir)
                  if p.endswith(aotcache.ENTRY_SUFFIX))


# ---------------------------------------------------------------------------
# key sensitivity: flip each fingerprint component -> different key
# ---------------------------------------------------------------------------

def _engine(app=None, lat_epochs=1, bw_up=None, **cfg_kw):
    """A tiny engine (construction traces nothing, so this is cheap):
    4 hosts on a 1-vertex graph, optionally with a stacked fault
    epoch table."""
    if app is None:
        app = PholdDevice(n_hosts_total=4, msgload=2, size=100,
                          selfloop=False)
    if lat_epochs == 1:
        lat = np.full((1, 1), 10**6, dtype=np.int64)
        rel = np.ones((1, 1), dtype=np.float32)
        times = None
    else:
        lat = np.full((lat_epochs, 1, 1), 10**6, dtype=np.int64)
        rel = np.ones((lat_epochs, 1, 1), dtype=np.float32)
        times = np.arange(lat_epochs, dtype=np.int64) * 10**8
    return DeviceEngine(
        EngineConfig(n_hosts=4, **cfg_kw), app,
        host_vertex=np.zeros(4, dtype=np.int32),
        latency_ns=lat, reliability=rel, epoch_times=times,
        bw_up_bits=bw_up)


def test_program_key_flips_on_every_fingerprint_component(monkeypatch):
    base = aotcache.program_key(_engine(), "run")
    # deterministic: the identical engine reproduces the key
    assert aotcache.program_key(_engine(), "run") == base
    # a different program name is a different key
    assert aotcache.program_key(_engine(), "pop") != base

    variants = {
        # workload fingerprint (app scalars)
        "workload": _engine(app=PholdDevice(
            n_hosts_total=4, msgload=3, size=100, selfloop=False)),
        # capacity knobs (each of the six feeds program_facts; one
        # representative per overflow family)
        "event_capacity": _engine(event_capacity=128),
        "outbox_capacity": _engine(outbox_capacity=64),
        "exchange_in_capacity": _engine(exchange_in_capacity=7),
        "outbox_compact": _engine(outbox_compact=9),
        # exchange variant
        "exchange": _engine(exchange="all_gather"),
        # fault epoch count
        "fault_epochs": _engine(lat_epochs=2),
        # audit flag
        "audit": _engine(audit=True),
        # trace-shaping schedule constants
        "lookahead": _engine(lookahead=123456),
        # the fluid NIC bakes the bandwidth vectors into the trace —
        # under model_bandwidth they must key the entry
        "model_bandwidth": _engine(model_bandwidth=True),
        "bandwidths": _engine(model_bandwidth=True,
                              bw_up=np.full(4, 5 * 10**6,
                                            dtype=np.int64)),
    }
    keys = {name: aotcache.program_key(e, "run")
            for name, e in variants.items()}
    for name, key in keys.items():
        assert key != base, f"{name} did not change the program key"
    assert len(set(keys.values())) == len(keys), \
        "two distinct variants collided on one key"

    # engine-code digest: a code change invalidates every entry
    monkeypatch.setattr(aotcache, "code_digest", lambda: "deadbeef")
    assert aotcache.program_key(_engine(), "run") != base

    # backend identity (versions + platform + device ids) is in the
    # signature, so a jax upgrade or a different mesh can never
    # resurrect a stale executable
    sig = aotcache.program_signature(_engine(), "run")
    for field in ("jax", "jaxlib", "platform", "device_ids"):
        assert field in sig["backend"]


# ---------------------------------------------------------------------------
# hit bit-identity + corrupted-entry fallback (one compile, reused)
# ---------------------------------------------------------------------------

def test_hit_bitmatch_and_corrupt_entry_recompiles(tmp_path):
    cache_dir = str(tmp_path / "aot")

    # cold run: miss, compile, store
    s1, c1 = _run(cache_dir)
    assert s1.ok
    ref = _sig(s1, c1)
    rep1 = s1.compile_cache
    assert rep1["misses"] == 1 and rep1["hits"] == 0
    assert rep1["events"][0]["program"] == "run"
    assert rep1["events"][0]["stored"] is True
    assert rep1["compile_s"] > 0
    entries = _entries(cache_dir)
    assert len(entries) == 1

    # warm run: hit, no compile, bit-identical
    s2, c2 = _run(cache_dir)
    assert s2.ok
    assert _sig(s2, c2) == ref
    rep2 = s2.compile_cache
    assert rep2["hits"] == 1 and rep2["misses"] == 0
    assert rep2["compile_s"] == 0
    assert rep2["load_s"] > 0

    # corrupted entry: truncate it mid-payload — the run must warn,
    # recompile, overwrite, and stay bit-identical (degradation is
    # to a fresh compile, never a wrong trace)
    path = os.path.join(cache_dir, entries[0])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 3)
    s3, c3 = _run(cache_dir)
    assert s3.ok
    assert _sig(s3, c3) == ref
    rep3 = s3.compile_cache
    assert rep3["hits"] == 0 and rep3["misses"] == 1
    # the overwrite healed the entry: a fourth run hits again
    assert os.path.getsize(path) > size // 3
    s4, c4 = _run(cache_dir)
    assert s4.compile_cache["hits"] == 1
    assert _sig(s4, c4) == ref

    # garbage that unpickles but is not an entry is equally survivable
    with open(path, "wb") as f:
        pickle.dump({"format": 999, "key": "wrong"}, f)
    s5, c5 = _run(cache_dir)
    assert s5.ok and _sig(s5, c5) == ref
    assert s5.compile_cache["hits"] == 0


def test_cache_off_runs_plain(tmp_path):
    s, c = _run("off")
    assert s.ok
    assert s.compile_cache is None


# ---------------------------------------------------------------------------
# LRU eviction under a size cap
# ---------------------------------------------------------------------------

def test_lru_eviction_under_tiny_cap(tmp_path):
    from shadow_tpu._jax import jax, jnp

    cache_dir = str(tmp_path / "lru")
    # compile three trivial distinct programs
    compiled = []
    for k in range(3):
        f = jax.jit(lambda x, k=k: x * (k + 2))
        compiled.append(f.lower(jnp.ones((4,))).compile())
    probe = aotcache.AotCache(cache_dir)
    assert probe.store("key0", compiled[0], {})
    entry_size = os.path.getsize(probe.entry_path("key0"))

    # cap admits two entries; storing a third evicts the LRU one
    cache = aotcache.AotCache(cache_dir,
                              cap_bytes=int(entry_size * 2.5))
    now = time.time()
    os.utime(cache.entry_path("key0"), (now - 300, now - 300))
    assert cache.store("key1", compiled[1], {})
    os.utime(cache.entry_path("key1"), (now - 200, now - 200))
    assert cache.store("key2", compiled[2], {})
    names = _entries(cache_dir)
    assert "key0" + aotcache.ENTRY_SUFFIX not in names, \
        "LRU entry survived past the cap"
    assert "key2" + aotcache.ENTRY_SUFFIX in names
    # a load TOUCHES the entry, protecting it from the next eviction
    assert cache.load("key1") is not None
    assert os.path.getmtime(cache.entry_path("key1")) >= now - 5


# ---------------------------------------------------------------------------
# concurrent writers: two processes racing on one entry
# ---------------------------------------------------------------------------

CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from shadow_tpu.device import aotcache
f = jax.jit(lambda x: x * 3 + 1)
compiled = f.lower(jnp.ones((8,))).compile()
cache = aotcache.AotCache({cache_dir!r})
ok = cache.store("shared_key", compiled, {{"writer": {tag}}})
print("stored", ok)
"""


def test_concurrent_writers_never_leave_a_torn_entry(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache_dir = str(tmp_path / "race")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             CHILD.format(repo=repo, cache_dir=cache_dir, tag=i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err
        assert "stored True" in out
    # whoever's os.replace landed second won; the file is COMPLETE
    # either way (pid-tagged tmp files cannot interleave)
    names = _entries(cache_dir)
    assert names == ["shared_key" + aotcache.ENTRY_SUFFIX]
    cache = aotcache.AotCache(cache_dir)
    loaded = cache.load("shared_key")
    assert loaded is not None
    import jax.numpy as jnp
    assert np.array_equal(np.asarray(loaded(jnp.ones((8,)))),
                          np.full(8, 4.0))
    with open(cache.entry_path("shared_key"), "rb") as f:
        entry = pickle.load(f)
    assert entry["meta"]["writer"] in (0, 1)
    # no tmp debris from either writer
    assert not [n for n in os.listdir(cache_dir)
                if n.endswith(".tmp")]


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

def test_schema_rejects_typod_compile_cache():
    bad = YAML.format(cache="atuo", extra="")
    with pytest.raises(ValueError, match="compile_cache"):
        load_config_str(bad)
    with pytest.raises(ValueError, match="compile_cache_cap_mb"):
        load_config_str(YAML.format(
            cache="auto", extra="  compile_cache_cap_mb: 0"))
    # keywords and path-looking values parse
    for ok in ("auto", "off", "./cache", "/tmp/x", "~/aot",
               "rel/dir"):
        cfg = load_config_str(YAML.format(cache=ok, extra=""))
        assert cfg.experimental.compile_cache == ok
