"""Device engine: prng bit-identity and trace equivalence vs the CPU
serial oracle — the core correctness argument of the TPU design."""

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.utils import nprng
from shadow_tpu.utils.rng import PURPOSE_APP, PURPOSE_PACKET_DROP


def test_device_prng_matches_numpy():
    from shadow_tpu.device import prng as dprng
    from shadow_tpu._jax import jnp
    seed = 42
    ids = np.array([0, 3, 17, 1000], dtype=np.uint32)
    seqs = np.array([0, 100, 2**20, 7], dtype=np.uint32)
    jk = dprng.chain_key(dprng.seed_key(seed), PURPOSE_PACKET_DROP,
                         jnp.asarray(ids), jnp.asarray(seqs))
    ju = np.asarray(dprng.uniform01(jk))
    nu = nprng.packet_uniform(seed, PURPOSE_PACKET_DROP, ids, seqs)
    np.testing.assert_array_equal(ju, nu)
    jb = np.asarray(dprng.random_bits32(dprng.chain_key(
        dprng.seed_key(seed), PURPOSE_APP, jnp.asarray(ids),
        jnp.asarray(seqs))))
    k = nprng.fold_in(nprng.fold_in(nprng.fold_in(
        nprng.seed_key(seed), PURPOSE_APP), ids), seqs)
    np.testing.assert_array_equal(jb, nprng.random_bits32(k))


PHOLD_YAML = """
general:
  stop_time: 2s
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "30 ms" packet_loss {loss} ]
        edge [ source 0 target 1 latency "10 ms" packet_loss {loss} ]
        edge [ source 1 target 1 latency "30 ms" packet_loss {loss} ]
      ]
experimental:
  scheduler_policy: {policy}
  event_capacity: 64
  outbox_capacity: 16
hosts:
  left:
    quantity: {q}
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload={msgload}
      start_time: 100ms
  right:
    quantity: {q}
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload={msgload}
      start_time: 150ms
"""


def _run(policy, seed=5, loss=0.0, q=8, msgload=2):
    yaml = PHOLD_YAML.format(policy=policy, seed=seed, loss=loss, q=q,
                             msgload=msgload)
    c = Controller(load_config_str(yaml))
    stats = c.run()
    hosts = c.sim.hosts
    return stats, hosts


@pytest.mark.parametrize("loss,msgload", [(0.0, 2), (0.1, 2), (0.0, 1)])
def test_device_matches_serial_oracle(loss, msgload):
    s_stats, s_hosts = _run("serial", loss=loss, msgload=msgload)
    d_stats, d_hosts = _run("tpu", loss=loss, msgload=msgload)
    assert d_stats.ok
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.packets_sent == d_stats.packets_sent
    assert s_stats.packets_dropped == d_stats.packets_dropped
    assert s_stats.packets_delivered == d_stats.packets_delivered
    for sh, dh in zip(s_hosts, d_hosts):
        assert sh.events_executed == dh.events_executed, sh.name
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_device_in_window_self_packets_match_oracle():
    # runahead larger than the self-path latency: self packets deliver
    # inside the window and must execute in-window, in timestamp order
    yaml = """
general: {{stop_time: 1s, seed: 4}}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ] ]
experimental:
  scheduler_policy: {policy}
  runahead: 100 ms
hosts:
  peer:
    quantity: 4
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload=2 selfloop=1
      start_time: 5ms
"""
    s = Controller(load_config_str(yaml.format(policy="serial")))
    s_stats = s.run()
    d = Controller(load_config_str(yaml.format(policy="tpu")))
    d_stats = d.run()
    assert d_stats.ok
    assert s_stats.events_executed == d_stats.events_executed
    assert s_stats.rounds == d_stats.rounds
    for sh, dh in zip(s.sim.hosts, d.sim.hosts):
        assert sh.trace_checksum == dh.trace_checksum, sh.name


def test_threaded_policy_propagates_app_errors():
    yaml = """
general: {stop_time: 1s, seed: 1}
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler_policy: host, runahead: 10 ms}
hosts:
  client:
    processes:
    - path: model:tgen_client
      args: server=nonexistent
      start_time: 1ms
"""
    c = Controller(load_config_str(yaml))
    with pytest.raises(RuntimeError, match="worker thread failed"):
        c.run()


def test_exchange_modes_identical_traces():
    """all_to_all exchanges only each shard pair's rows; all_gather
    replicates everything. Same rows, same deterministic arrival order
    -> bit-identical traces on the 8-device mesh."""
    yaml = PHOLD_YAML.format(policy="tpu", seed=6, loss=0.05, q=8,
                             msgload=2)
    out = {}
    for mode in ("all_gather", "all_to_all"):
        c = Controller(load_config_str(
            yaml.replace("experimental:",
                         f"experimental:\n  exchange: {mode}")))
        stats = c.run()
        assert stats.ok, mode
        out[mode] = [h.trace_checksum for h in c.sim.hosts]
    assert out["all_gather"] == out["all_to_all"]


def test_exchange_capacity_overflow_detected():
    """A deliberately tiny per-pair capacity must fail the run loudly
    (overflow counted per source host), never silently drop rows."""
    yaml = PHOLD_YAML.format(policy="tpu", seed=6, loss=0.0, q=8,
                             msgload=4)
    c = Controller(load_config_str(
        yaml.replace("experimental:",
                     "experimental:\n  exchange_capacity: 1")))
    stats = c.run()
    assert not stats.ok


def test_dispatch_segment_trace_invariant():
    """Bounding the sim-time of each device dispatch (the tunneled-
    relay watchdog workaround) splits one run into several invocations
    of the same compiled program; window clamping stays on the global
    stop, so the trace must be bit-identical."""
    base = PHOLD_YAML.format(policy="tpu", seed=5, loss=0.1, q=8,
                             msgload=2)
    seg = base.replace("experimental:",
                       "experimental:\n  dispatch_segment: 300ms")
    outs = []
    for yaml in (base, seg):
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok
        outs.append((stats.events_executed, stats.packets_sent,
                     [h.trace_checksum for h in c.sim.hosts]))
    assert outs[0] == outs[1]


def test_judge_placement_identical_traces_phold():
    """Hoisted vs in-step judgment on the multi-send-lane phold app
    (K > 1, no trains): bit-identical traces and stats."""
    outs = {}
    for placement in ("step", "flush"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                                 msgload=3)
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  judge_placement: {placement}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, placement
        outs[placement] = (stats.events_executed, stats.packets_sent,
                           stats.packets_dropped,
                           [h.trace_checksum for h in c.sim.hosts])
    assert outs["step"] == outs["flush"]


def test_merge_strategy_identical_traces_phold():
    """Gatherless global double-sort merge vs the flat-sort + window
    merge: same arrival sets, same (time, src, seq) per-host order,
    bit-identical traces — on lossy multi-lane phold over the
    8-device mesh (exercises the all_to_all pack + self-shard bypass
    feeding the global merge)."""
    outs = {}
    for strategy in ("window", "global"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                                 msgload=3)
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  merge_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["window"] == outs["global"]


def test_tpu_default_knobs_identical_traces():
    """The combination production TPU actually runs — judgment
    hoisted to flush AND the global double-sort merge together
    (_judge_outbox rewrites ob t/m/v, then _ob_rows re-reads them) —
    pinned against the CPU-default step+window combination."""
    outs = {}
    for extra in ("  judge_placement: step\n  merge_strategy: window\n"
                  "  pop_strategy: gather",
                  "  judge_placement: flush\n  merge_strategy: global\n"
                  "  pop_strategy: onehot"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                                 msgload=3)
        yaml = yaml.replace("experimental:",
                            "experimental:\n" + extra)
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, extra
        outs[extra] = (stats.events_executed, stats.packets_sent,
                       stats.packets_dropped,
                       [h.trace_checksum for h in c.sim.hosts])
    a, b = outs.values()
    assert a == b


def test_pop_strategy_identical_traces_phold():
    """One-hot masked-reduction head reads vs take_along_axis: the
    pop loop must yield the same event order (and thus bit-identical
    traces) on lossy multi-lane phold over the 8-device mesh."""
    outs = {}
    for strategy in ("gather", "onehot"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                                 msgload=3)
        yaml = yaml.replace(
            "experimental:",
            f"experimental:\n  pop_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["gather"] == outs["onehot"]


def test_merge_strategy_identical_traces_all_gather():
    """The all_gather exchange fallback under the global merge:
    every shard replicates raw outbox rows and keeps its own via the
    destination mask; traces must match the window path."""
    outs = {}
    for strategy in ("window", "global"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=3, loss=0.05, q=8,
                                 msgload=2)
        yaml = yaml.replace(
            "experimental:",
            "experimental:\n  exchange: all_gather\n"
            f"  merge_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["window"] == outs["global"]


def test_table_strategy_identical_traces():
    """One-hot topology-table lookups vs indexed gathers in the
    hoisted judge (lossy, so relv feeds real drop rolls): selection
    is exact, traces must bit-match."""
    outs = {}
    for strategy in ("gather", "onehot"):
        yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                                 msgload=3)
        yaml = yaml.replace(
            "experimental:",
            "experimental:\n  judge_placement: flush\n"
            f"  table_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        outs[strategy] = (stats.events_executed, stats.packets_sent,
                          stats.packets_dropped,
                          [h.trace_checksum for h in c.sim.hosts])
    assert outs["gather"] == outs["onehot"]


def test_outbox_compact_global_identical_traces():
    """Gatherless compaction on the GLOBAL merge path (lane sort +
    static slice): with a width that fits the real per-host fan-out,
    traces must bit-match the uncompacted global merge — on the
    8-device mesh over both exchanges (all_to_all self-shard rows and
    the all_gather replication, whose ICI volume compaction cuts)."""
    for exchange in ("all_to_all", "all_gather"):
        outs = {}
        for cx in (0, 12):
            yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1,
                                     q=8, msgload=3)
            yaml = yaml.replace(
                "experimental:",
                f"experimental:\n  exchange: {exchange}\n"
                f"  merge_strategy: global\n  outbox_compact: {cx}")
            c = Controller(load_config_str(yaml))
            stats = c.run()
            assert stats.ok, (exchange, cx)
            outs[cx] = (stats.events_executed, stats.packets_sent,
                        stats.packets_dropped,
                        [h.trace_checksum for h in c.sim.hosts])
        assert outs[0] == outs[12], exchange


def test_outbox_compact_global_overflow_detected():
    """A compaction width smaller than a host's real per-phase
    fan-out must fail LOUDLY (x_overflow), never silently drop."""
    yaml = PHOLD_YAML.format(policy="tpu", seed=7, loss=0.1, q=8,
                             msgload=3)
    yaml = yaml.replace(
        "experimental:",
        "experimental:\n  merge_strategy: global\n"
        "  outbox_compact: 1")
    c = Controller(load_config_str(yaml))
    stats = c.run()
    assert not stats.ok


def test_merge_global_overflow_detected():
    """Hub skew under the global merge: 999 clients hammering one
    server must fail LOUDLY at small event_capacity (rank-based
    overflow, same contract as the window path's arrival-window
    overflow) and, once the knob is raised, bit-match the window
    path."""
    yaml = HUB_YAML.format(exchange="all_to_all", ecap=64).replace(
        "experimental:", "experimental:\n  merge_strategy: global")
    c = Controller(load_config_str(yaml))
    stats = c.run()
    assert not stats.ok

    out = {}
    for strategy in ("window", "global"):
        yaml = HUB_YAML.format(exchange="all_to_all",
                               ecap=1024).replace(
            "experimental:",
            f"experimental:\n  merge_strategy: {strategy}")
        c = Controller(load_config_str(yaml))
        stats = c.run()
        assert stats.ok, strategy
        out[strategy] = [h.trace_checksum for h in c.sim.hosts]
    assert out["window"] == out["global"]


def test_device_deterministic_across_runs():
    _, h1 = _run("tpu", seed=9)
    _, h2 = _run("tpu", seed=9)
    assert [h.trace_checksum for h in h1] == \
        [h.trace_checksum for h in h2]
    _, h3 = _run("tpu", seed=10)
    assert [h.trace_checksum for h in h1] != \
        [h.trace_checksum for h in h3]


def test_device_app_state_matches_cpu():
    from shadow_tpu.core.controller import Controller as C
    yaml = PHOLD_YAML.format(policy="serial", seed=3, loss=0.05, q=4,
                             msgload=1)
    c = C(load_config_str(yaml))
    c.run()
    cpu_recv = [h.app.received for h in c.sim.hosts]

    yaml = PHOLD_YAML.format(policy="tpu", seed=3, loss=0.05, q=4,
                             msgload=1)
    c2 = C(load_config_str(yaml))
    c2.run()
    dev_recv = list(np.asarray(
        c2.runner.final_state["app"][:len(c2.sim.hosts), 0]))
    assert cpu_recv == dev_recv


def test_path_packet_counters_match_oracle():
    """topology_incrementPathPacketCounter parity (ref topology.c:1983):
    the device's flush-time [V,V] histogram equals the CPU oracle's
    per-path judged-packet counts — drop-rolled packets included."""
    from shadow_tpu.config import load_config_str

    def run(policy):
        yaml = PHOLD_YAML.format(policy=policy, seed=5, loss=0.1, q=8,
                                 msgload=2)
        yaml += "\n"
        cfg = load_config_str(
            yaml, overrides=["experimental.count_paths=true"])
        c = Controller(cfg)
        stats = c.run()
        assert stats.ok
        return dict(c.sim.netmodel.path_packets)

    s = run("serial")
    d = run("tpu")
    assert s and sum(s.values()) > 200
    assert s == d


HUB_YAML = """
general:
  stop_time: 4s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.001 ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.001 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.001 ]
      ]
experimental:
  scheduler_policy: tpu
  exchange: {exchange}
  event_capacity: {ecap}
hosts:
  server_hub:
    network_node_id: 0
    processes: [{{path: model:tgen_server, start_time: 1s}}]
  clients:
    quantity: 999
    network_node_id: 1
    processes:
    - {{path: model:tgen_client, args: server=server_hub size=4KiB count=1, start_time: 2s}}
"""


def test_hub_skew_exchange(caplog):
    """SURVEY hard-part #2 at skew: 999 clients all hammering ONE
    server shard (maximum (src,dst)-pair concentration). With default
    capacities the run must FAIL LOUDLY (the hub's per-flush arrival
    window overflows; no silent loss). With event_capacity raised,
    the auto-sized all_to_all CAP must hold — zero x_overflow — and
    bit-match the all_gather oracle on the same config."""
    import logging

    # 1: default capacities -> loud failure with the capacity knob
    # named in the error (never a wrong answer)
    c = Controller(load_config_str(
        HUB_YAML.format(exchange="all_to_all", ecap=64)))
    with caplog.at_level(logging.ERROR):
        stats = c.run()
    assert not stats.ok
    assert any("capacity" in r.message for r in caplog.records)

    # 2: the documented knob fixes it; auto CAP holds at full skew
    out = {}
    for mode in ("all_to_all", "all_gather"):
        c = Controller(load_config_str(
            HUB_YAML.format(exchange=mode, ecap=1024)))
        stats = c.run()
        assert stats.ok, mode
        x_of = int(np.asarray(
            c.runner.final_state["x_overflow"]).sum())
        assert x_of == 0, mode
        assert stats.packets_sent > 999     # requests + responses
        out[mode] = [h.trace_checksum for h in c.sim.hosts]
    assert out["all_to_all"] == out["all_gather"]


def test_self_shard_rows_bypass_exchange_capacity():
    """ADVICE r3 #4: self-shard rows (timers, local sends) never
    enter the all_to_all pack — a fully shard-local workload runs
    with exchange_capacity=1 and zero x_overflow (it used to consume
    CAP and overflow)."""
    yaml = """
general:
  stop_time: 4s
  seed: 2
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.01 ]
      ]
experimental:
  scheduler_policy: tpu
  exchange: all_to_all
  exchange_capacity: 1
hosts:
"""
    # 8 adjacent (server, client) pairs -> 16 hosts over the 8-device
    # mesh (H_loc=2): every pair is shard-local, all traffic self-shard
    for i in range(8):
        yaml += f"""  server{i}:
    network_node_id: 0
    processes: [{{path: model:tgen_server, start_time: 10ms}}]
  client{i}:
    network_node_id: 0
    processes:
    - {{path: model:tgen_client, args: server=server{i} size=64KiB count=2 pause=100ms, start_time: 100ms}}
"""
    c = Controller(load_config_str(yaml))
    stats = c.run()
    assert stats.ok
    assert int(np.asarray(c.runner.final_state["x_overflow"]).sum()) \
        == 0
    assert stats.packets_sent > 0
    # and the serial oracle agrees bit-for-bit
    c2 = Controller(load_config_str(
        yaml.replace("scheduler_policy: tpu",
                     "scheduler_policy: serial")))
    s2 = c2.run()
    assert s2.ok
    assert [h.trace_checksum for h in c2.sim.hosts] == \
        [h.trace_checksum for h in c.sim.hosts]
