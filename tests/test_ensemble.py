"""Ensemble engine: vmapped multi-replica campaigns.

Fast layers (schema validation, replica-world building, aggregation)
run in tier-1; everything that compiles a DeviceEngine program is
marked slow (the tier-1 budget rule). The full campaign determinism
matrix — replica-0 vs standalone serial AND tpu — additionally runs
in CI via `determinism_gate.py --ensemble` on
examples/ensemble_seed_sweep.yaml.
"""

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.config.schema import EnsembleOptions

SMALL = """
general: {{stop_time: 1500ms, seed: 1}}
network:
  graph:
    type: gml
    inline: |
      graph [ directed 0
        node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "5 ms" packet_loss 0.02 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ] ]
experimental:
  scheduler_policy: tpu
{ensemble}
hosts:
  server:
    network_node_id: 0
    processes: [{{path: "model:tgen_server", start_time: 50ms}}]
  client:
    quantity: 4
    network_node_id: 1
    processes:
    - path: model:tgen_client
      args: server=server size=60KiB count=2 pause=100ms retry=300ms
      start_time: 100ms
"""

ENSEMBLE_BLOCK = """
ensemble:
  replicas: 2
  vary:
    seed: [1, 9]
"""


def _cfg(ensemble: str = ""):
    return load_config_str(SMALL.format(ensemble=ensemble))


# ------------------------------------------------------------- schema
def test_schema_requires_tpu_policy():
    bad = SMALL.format(ensemble=ENSEMBLE_BLOCK).replace(
        "scheduler_policy: tpu", "scheduler_policy: serial")
    with pytest.raises(ValueError, match="scheduler_policy: tpu"):
        load_config_str(bad)


def test_schema_vary_length_must_match_replicas():
    with pytest.raises(ValueError, match="one.*value per replica"):
        EnsembleOptions.from_dict(
            {"replicas": 3, "vary": {"seed": [1, 2]}})


def test_schema_rejects_unknown_axis_and_empty_vary():
    with pytest.raises(ValueError, match="unknown key"):
        EnsembleOptions.from_dict(
            {"replicas": 2, "vary": {"stop_time": [1, 2]}})
    with pytest.raises(ValueError, match="empty vary"):
        EnsembleOptions.from_dict({"replicas": 2})


def test_schema_rejects_bad_axis_values():
    with pytest.raises(ValueError, match="latency_scale"):
        EnsembleOptions.from_dict(
            {"replicas": 2, "vary": {"latency_scale": [1.0, 0.0]}})
    with pytest.raises(ValueError, match="packet_loss_delta"):
        EnsembleOptions.from_dict(
            {"replicas": 2, "vary": {"packet_loss_delta": [0.0, 1.5]}})


def test_schema_fault_schedule_rules():
    # unknown schedule name
    with pytest.raises(ValueError, match="unknown schedule"):
        EnsembleOptions.from_dict(
            {"replicas": 2,
             "vary": {"fault_schedule": ["base", "storm"]}})
    # reserved names
    with pytest.raises(ValueError, match="reserved"):
        EnsembleOptions.from_dict(
            {"replicas": 1, "vary": {"seed": [1]},
             "fault_schedules": {"base": []}})
    # host faults are manager-side: never in a campaign schedule
    with pytest.raises(ValueError, match="host faults"):
        EnsembleOptions.from_dict(
            {"replicas": 2,
             "vary": {"fault_schedule": ["base", "crashy"]},
             "fault_schedules": {"crashy": [
                 {"kind": "host_crash", "time": "1s",
                  "host": "client0"}]}})


def test_schema_aggregate_choices():
    opts = EnsembleOptions.from_dict(
        {"replicas": 2, "vary": {"seed": [1, 2]},
         "aggregate": ["mean", "max"]})
    assert opts.aggregate == ("mean", "max")
    with pytest.raises(ValueError, match="aggregate"):
        EnsembleOptions.from_dict(
            {"replicas": 2, "vary": {"seed": [1, 2]},
             "aggregate": ["median"]})


# ------------------------------------------------------ worlds (spec)
def _worlds(cfg):
    from shadow_tpu.core.controller import build
    from shadow_tpu.ensemble.spec import build_worlds

    sim = build(cfg)
    return build_worlds(sim, cfg.ensemble)


def test_worlds_seed_sweep_keys_match_engine_seed_key():
    from shadow_tpu.device import prng

    cfg = _cfg(ENSEMBLE_BLOCK)
    w = _worlds(cfg)
    assert w.R == 2
    assert w.latency.shape[0] == 2 and w.latency.ndim == 3
    assert (w.epoch_times == 0).all()          # fault-free: T == 1
    for r, seed in enumerate([1, 9]):
        k1, k2 = prng.seed_key(seed)
        assert int(w.seed_k1[r]) == int(k1)
        assert int(w.seed_k2[r]) == int(k2)
    # replica 0 is the base world bit-for-bit
    assert w.descriptors[0]["seed"] == 1


def test_worlds_fault_schedule_padding_and_lookahead():
    from shadow_tpu.ensemble.spec import FAR_EPOCH

    block = """
ensemble:
  replicas: 2
  vary:
    fault_schedule: [none, slow]
  fault_schedules:
    slow:
      - {kind: degrade, time: 500ms, duration: 200ms, source: 0,
         target: 1, latency_multiplier: 3}
"""
    cfg = _cfg(block)
    w = _worlds(cfg)
    # degrade creates epochs [0, 500ms, 700ms]; the fault-free
    # replica pads to the shared T with never-reached epochs
    assert w.epoch_times.shape == (2, 3)
    assert list(w.epoch_times[1]) == [0, 500_000_000, 700_000_000]
    assert w.epoch_times[0][0] == 0
    assert (w.epoch_times[0][1:] == FAR_EPOCH).all()
    # padded epochs repeat the last real matrices
    assert (w.latency[0][0] == w.latency[0][1]).all()
    # lookahead = min over every replica's every epoch (degrade only
    # raises latency, so the base 5 ms floor stands)
    assert w.lookahead == 5_000_000


def test_worlds_loss_delta_and_scale():
    block = """
ensemble:
  replicas: 2
  vary:
    latency_scale: [1.0, 2.0]
    packet_loss_delta: [0.0, 0.5]
"""
    cfg = _cfg(block)
    w = _worlds(cfg)
    assert (w.latency[1] == 2 * w.latency[0]).all()
    assert np.allclose(
        np.clip(w.reliability[0] - 0.5, 0.0, 1.0), w.reliability[1])
    assert w.lookahead == int(w.latency[0].min())


def test_campaign_fingerprint_tracks_vary():
    cfg_a = _cfg(ENSEMBLE_BLOCK)
    cfg_b = _cfg(ENSEMBLE_BLOCK.replace("[1, 9]", "[1, 10]"))
    assert _worlds(cfg_a).campaign_fp != _worlds(cfg_b).campaign_fp
    # same vary -> same fingerprint (stable identity for resume)
    assert _worlds(cfg_a).campaign_fp == _worlds(cfg_a).campaign_fp


# -------------------------------------------------------- aggregation
def test_aggregate_ops():
    from shadow_tpu.ensemble.campaign import aggregate

    vals = [10, 20, 30, 40]
    agg = aggregate(vals, ("mean", "p5", "p95", "min", "max"))
    assert agg["mean"] == 25.0
    assert agg["min"] == 10.0 and agg["max"] == 40.0
    assert 10.0 <= agg["p5"] <= 20.0
    assert 30.0 <= agg["p95"] <= 40.0
    assert aggregate([7], ("mean",)) == {"mean": 7.0}


# ---------------------------------------------- campaign runs (slow)
@pytest.mark.slow
def test_campaign_replica_bit_identity_and_record(tmp_path):
    """The tentpole contract on a small seed sweep: every replica's
    slice bit-matches a standalone device run with that replica's
    seed, campaign totals are the per-replica sums, and the ENSEMBLE
    record lands with per-replica checksums + aggregates. (The CI
    gate additionally pins replica-0 against the serial oracle.)"""
    from shadow_tpu.core.controller import Controller

    rec_path = tmp_path / "ENSEMBLE_test.json"
    block = ENSEMBLE_BLOCK + f"  record_path: {rec_path}\n"
    cfg = _cfg(block)
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok
    final = c.runner.final_state
    H = len(c.sim.hosts)

    total = 0
    for r, seed in enumerate([1, 9]):
        cfg2 = _cfg()
        cfg2.general.seed = seed
        c2 = Controller(cfg2)
        s2 = c2.run()
        assert s2.ok
        chk = np.array([h.trace_checksum for h in c2.sim.hosts])
        assert (chk == final["chk"][r, :H]).all(), \
            f"replica {r} diverged from standalone seed {seed}"
        assert (np.array([h.events_executed for h in c2.sim.hosts])
                == final["n_exec"][r, :H]).all()
        total += s2.packets_sent
    assert stats.packets_sent == total
    assert stats.ensemble is not None

    # replica 0's results surface on the Host objects (gate contract)
    assert [h.trace_checksum for h in c.sim.hosts] == \
        [int(x) for x in final["chk"][0, :H]]

    import json
    with open(rec_path) as f:
        rec = json.load(f)
    assert rec["campaign"] == c.runner.worlds.campaign_fp
    assert len(rec["replicas"]) == 2
    assert rec["replicas"][1]["seed"] == 9
    assert rec["replicas"][0]["host_checksums"] == \
        [int(x) for x in final["chk"][0, :H]]
    agg = rec["aggregates"]["packets_sent"]
    assert agg["min"] <= agg["mean"] <= agg["max"]
    assert rec["ok"] is True


@pytest.mark.slow
def test_campaign_checkpoint_resume_and_guards(tmp_path):
    """Checkpointing a campaign stamps the campaign fingerprint;
    resume restores all replicas bit-identically; an edited vary
    block or a standalone run refuses the saved state."""
    from shadow_tpu.core.controller import Controller

    rec = tmp_path / "rec.json"
    block = ENSEMBLE_BLOCK + f"  record_path: {rec}\n"

    ref = Controller(_cfg(block))
    assert ref.run().ok
    ref_chk = ref.runner.final_state["chk"].copy()

    ck = str(tmp_path / "camp.npz")
    cfg = _cfg(block)
    cfg.experimental.checkpoint_save = ck
    cfg.experimental.checkpoint_save_time = 800_000_000
    s1 = Controller(cfg).run()
    assert s1.end_time == 800_000_000

    cfg2 = _cfg(block)
    cfg2.experimental.checkpoint_load = ck
    c2 = Controller(cfg2)
    assert c2.run().ok
    assert (np.asarray(c2.runner.final_state["chk"])
            == np.asarray(ref_chk)).all()

    # edited campaign -> fingerprint mismatch, refused
    cfg3 = _cfg(block.replace("[1, 9]", "[1, 11]"))
    cfg3.experimental.checkpoint_load = ck
    with pytest.raises(ValueError, match="campaign"):
        Controller(cfg3).run()

    # standalone run -> campaign checkpoints are not loadable
    cfg4 = _cfg()
    cfg4.experimental.checkpoint_load = ck
    with pytest.raises(ValueError, match="ensemble campaign"):
        Controller(cfg4).run()


@pytest.mark.slow
def test_campaign_capacity_plan_worst_case(tmp_path):
    """capacity_plan: auto on a campaign sizes from the worst-case
    replica's warm-up occupancy; traces stay bit-identical to the
    statically-sized campaign."""
    from shadow_tpu.core.controller import Controller

    block = ENSEMBLE_BLOCK + f"  record_path: {tmp_path / 'a.json'}\n"
    ref = Controller(_cfg(block))
    assert ref.run().ok
    ref_chk = ref.runner.final_state["chk"].copy()

    import os
    os.environ["SHADOW_TPU_OCC_DIR"] = str(tmp_path)
    try:
        cfg = _cfg(block.replace("a.json", "b.json"))
        cfg.experimental.capacity_plan = "auto"
        c = Controller(cfg)
        stats = c.run()
        assert stats.ok
        assert stats.occupancy["planned"]["event_capacity"] >= 2
        assert (np.asarray(c.runner.final_state["chk"])
                == np.asarray(ref_chk)).all()
    finally:
        del os.environ["SHADOW_TPU_OCC_DIR"]
