/* Prints simulated clocks before/after a sleep; determinism probe.
 * Mirrors the role of the reference's src/test/sleep + determinism
 * suites: under the simulator, the printed times are exact functions
 * of the config, not of wall time. */
#include <stdio.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  printf("t0 %ld.%09ld\n", (long)ts.tv_sec, ts.tv_nsec);

  usleep(100000); /* 100 ms simulated */

  clock_gettime(CLOCK_MONOTONIC, &ts);
  printf("t1 %ld.%09ld\n", (long)ts.tv_sec, ts.tv_nsec);

  struct timespec tw;
  clock_gettime(CLOCK_REALTIME, &tw);
  printf("wall %ld\n", (long)tw.tv_sec);

  char host[64];
  gethostname(host, sizeof host);
  printf("host %s\n", host);
  printf("pid %d\n", (int)getpid());
  fflush(stdout);
  return 0;
}
