/* sendfile(2) source: writes a deterministic pattern file in its cwd
 * (the host data dir), then streams it to the server with
 * sendfile(out=socket, in=file) and prints the expected checksum.
 * Exercises the emulated sendfile path (the reference leaves sendfile
 * unimplemented, syscall_handler.c:434 — this framework emulates it by
 * streaming the file bytes through the in-simulator TCP socket). */
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: sendfile_client <ip> <port> <nbytes>\n");
    return 2;
  }
  const char *ip = argv[1];
  int port = atoi(argv[2]);
  long nbytes = atol(argv[3]);

  /* build the pattern file */
  int f = open("payload.bin", O_CREAT | O_TRUNC | O_WRONLY, 0644);
  if (f < 0) {
    perror("open w");
    return 1;
  }
  unsigned long sum = 0;
  char buf[8192];
  for (long off = 0; off < nbytes;) {
    long chunk = nbytes - off;
    if (chunk > (long)sizeof buf)
      chunk = (long)sizeof buf;
    for (long i = 0; i < chunk; i++) {
      buf[i] = (char)((off + i) * 131 + 7);
      sum = (sum * 31 + (unsigned char)buf[i]) & 0xFFFFFFFFUL;
    }
    if (write(f, buf, chunk) != chunk) {
      perror("write");
      return 1;
    }
    off += chunk;
  }
  close(f);

  int s = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons(port);
  dst.sin_addr.s_addr = inet_addr(ip);
  if (connect(s, (struct sockaddr *)&dst, sizeof dst) != 0) {
    perror("connect");
    return 1;
  }

  int in = open("payload.bin", O_RDONLY);
  if (in < 0) {
    perror("open r");
    return 1;
  }
  off_t off = 0;
  long sent = 0;
  while (sent < nbytes) {
    ssize_t r = sendfile(s, in, &off, (size_t)(nbytes - sent));
    if (r < 0) {
      perror("sendfile");
      return 1;
    }
    if (r == 0)
      break;
    sent += r;
  }
  printf("sendfile sent %ld bytes sum %lu off %ld\n", sent, sum,
         (long)off);
  close(in);
  close(s);
  fflush(stdout);
  return 0;
}
