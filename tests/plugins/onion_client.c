/* Onion-routed source: connects to the entry relay, writes one
 * stacked forwarding header per hop (each relay peels one line),
 * then streams <nbytes> of the tcp_client pattern. args:
 *   <entry_ip> <entry_port> <nbytes> [next_ip next_port]... */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 4 || (argc - 4) % 2 != 0) {
    fprintf(stderr, "usage: onion_client <ip> <port> <nbytes> "
                    "[next_ip next_port]...\n");
    return 2;
  }
  long nbytes = atol(argv[3]);
  int s = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons(atoi(argv[2]));
  dst.sin_addr.s_addr = inet_addr(argv[1]);
  if (connect(s, (struct sockaddr *)&dst, sizeof dst) != 0) {
    perror("connect");
    return 1;
  }
  printf("connected\n");
  for (int i = 4; i + 1 < argc; i += 2) {
    char hdr[128];
    int n = snprintf(hdr, sizeof hdr, "%s %s\n", argv[i], argv[i + 1]);
    if (write(s, hdr, (size_t)n) != n) { perror("hdr"); return 1; }
  }
  char buf[8192];
  unsigned long sum = 0;
  long sent = 0;
  while (sent < nbytes) {
    long chunk = nbytes - sent;
    if (chunk > (long)sizeof buf) chunk = (long)sizeof buf;
    for (long i = 0; i < chunk; i++)
      buf[i] = (char)((sent + i) * 131 + 7);
    long off = 0;
    while (off < chunk) {
      ssize_t w = write(s, buf + off, (size_t)(chunk - off));
      if (w < 0) { perror("write"); return 1; }
      off += w;
    }
    for (long i = 0; i < chunk; i++)
      sum = (sum * 31 + (unsigned char)buf[i]) & 0xFFFFFFFFUL;
    sent += chunk;
  }
  printf("sent %ld bytes sum %lu\n", sent, sum);
  close(s);
  fflush(stdout);
  return 0;
}
