/* Signal masks + synchronous waits under the virtual signal layer:
 * blocked signals stay pending (sigpending sees them), delivery
 * happens at the unblocking boundary, sigsuspend atomically swaps the
 * mask and returns EINTR after one handler, and sigtimedwait consumes
 * a queued signal synchronously (no handler) or times out with EAGAIN
 * at the exact simulated deadline. */
#define _GNU_SOURCE
#include <errno.h>
#include <poll.h>
#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t got1 = 0, got2 = 0, term_handled = 0;

static void h1(int sig) { (void)sig; got1++; }
static void h2(int sig) { (void)sig; got2++; }
static void hterm(int sig) { (void)sig; term_handled++; }

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static volatile sig_atomic_t t_phase = 0;

static void *blocker(void *arg) {
  (void)arg;
  sigset_t m;
  sigemptyset(&m);
  sigaddset(&m, SIGUSR1);
  pthread_sigmask(SIG_BLOCK, &m, NULL);
  t_phase = 1;
  while (t_phase == 1)
    usleep(10 * 1000);          /* main sends the directed signal */
  int before = (int)got1;
  usleep(50 * 1000);            /* still blocked: handler must wait */
  int during = (int)got1 - before;
  pthread_sigmask(SIG_UNBLOCK, &m, NULL);
  (void)now_ms();               /* a boundary after the unblock */
  printf("directed held %d delivered %d\n", during == 0,
         (int)got1 - before);
  return NULL;
}

int main(void) {
  signal(SIGUSR1, h1);
  signal(SIGUSR2, h2);

  /* 1: block SIGUSR1, self-kill — handler must NOT run; pending set
   * shows it; unblock — handler runs at that boundary */
  sigset_t blk, old, pend;
  sigemptyset(&blk);
  sigaddset(&blk, SIGUSR1);
  sigprocmask(SIG_BLOCK, &blk, &old);
  kill(getpid(), SIGUSR1);
  int before = got1;
  sigpending(&pend);
  int was_pending = sigismember(&pend, SIGUSR1);
  sigprocmask(SIG_UNBLOCK, &blk, NULL);
  /* one more trapped syscall boundary so the flush has landed */
  (void)now_ms();
  printf("blocked %d pending %d after_unblock %d\n", before == 0,
         was_pending, (int)got1);

  /* 2: sigsuspend — USR2 pending while blocked; suspend with a mask
   * that admits it: handler runs, EINTR, old mask back in force */
  sigemptyset(&blk);
  sigaddset(&blk, SIGUSR2);
  sigprocmask(SIG_BLOCK, &blk, NULL);
  kill(getpid(), SIGUSR2);
  sigset_t none;
  sigemptyset(&none);
  int sr = sigsuspend(&none);
  sigset_t cur;
  sigprocmask(SIG_BLOCK, NULL, &cur);
  printf("sigsuspend %d errno_ok %d got2 %d mask_restored %d\n",
         sr == -1, errno == EINTR, (int)got2,
         sigismember(&cur, SIGUSR2));
  sigprocmask(SIG_UNBLOCK, &blk, NULL);

  /* 3: sigtimedwait consumes a child's SIGTERM synchronously at the
   * simulated send instant — the handler must NOT run */
  signal(SIGTERM, hterm);
  sigemptyset(&blk);
  sigaddset(&blk, SIGTERM);
  sigprocmask(SIG_BLOCK, &blk, NULL);
  long t0 = now_ms();
  pid_t child = fork();
  if (child == 0) {
    usleep(100 * 1000);
    kill(getppid(), SIGTERM);
    _exit(0);
  }
  siginfo_t si;
  memset(&si, 0, sizeof si);
  int w = sigtimedwait(&blk, &si, NULL);
  long dt = now_ms() - t0;
  printf("sigtimedwait %d si_signo %d handler_ran %d t_ms %ld\n",
         w == SIGTERM, si.si_signo, (int)term_handled, dt);
  int st;
  waitpid(child, &st, 0);

  /* 3b: the reaper idiom — SIGCHLD (default-ignore) raised while
   * blocked and BEFORE the wait starts must still be queued, so a
   * later sigtimedwait consumes it instantly */
  sigset_t chld;
  sigemptyset(&chld);
  sigaddset(&chld, SIGCHLD);
  sigprocmask(SIG_BLOCK, &chld, NULL);
  pid_t quick = fork();
  if (quick == 0)
    _exit(0);
  usleep(50 * 1000);            /* child is long dead + queued */
  t0 = now_ms();
  struct timespec zero_plus = {5, 0};
  int wc = sigtimedwait(&chld, NULL, &zero_plus);
  dt = now_ms() - t0;
  printf("reaper %d instant %d\n", wc == SIGCHLD, dt == 0);
  waitpid(quick, &st, 0);
  sigprocmask(SIG_UNBLOCK, &chld, NULL);

  /* 4: sigtimedwait timeout — EAGAIN at exactly +250 ms sim time */
  sigset_t never;
  sigemptyset(&never);
  sigaddset(&never, SIGWINCH);
  sigprocmask(SIG_BLOCK, &never, NULL);
  struct timespec to = {0, 250 * 1000 * 1000};
  t0 = now_ms();
  int w2 = sigtimedwait(&never, NULL, &to);
  dt = now_ms() - t0;
  printf("timeout %d errno_ok %d t_ms %ld\n", w2 == -1,
         errno == EAGAIN, dt);

  /* 4b: ppoll's atomic mask swap — SIGUSR1 blocked outside the call;
   * the empty temp mask must let a child's signal interrupt the wait
   * (EINTR at the send instant), and the block is back afterwards */
  got1 = 0;
  sigemptyset(&blk);
  sigaddset(&blk, SIGUSR1);
  sigprocmask(SIG_BLOCK, &blk, NULL);
  t0 = now_ms();
  pid_t pinger = fork();
  if (pinger == 0) {
    usleep(80 * 1000);
    kill(getppid(), SIGUSR1);
    _exit(0);
  }
  struct timespec long_to = {5, 0};
  sigset_t empty;
  sigemptyset(&empty);
  int pr = ppoll(NULL, 0, &long_to, &empty);
  dt = now_ms() - t0;
  sigprocmask(SIG_BLOCK, NULL, &cur);
  printf("ppoll_eintr %d got1 %d t_ms %ld mask_back %d\n",
         pr == -1 && errno == EINTR, (int)got1, dt,
         sigismember(&cur, SIGUSR1));
  waitpid(pinger, &st, 0);
  sigprocmask(SIG_UNBLOCK, &blk, NULL);

  /* 5: thread-directed signals — pthread_kill at a thread that
   * blocks the signal must park it on THAT thread only: the main
   * thread (unblocked) never runs the handler, and delivery happens
   * at the target's own unblock boundary */
  got1 = 0;
  pthread_t th;
  pthread_create(&th, NULL, blocker, NULL);
  while (t_phase == 0)
    usleep(10 * 1000);
  pthread_kill(th, SIGUSR1);
  int main_saw = (int)got1;     /* boundary was pthread_kill itself */
  t_phase = 2;
  pthread_join(th, NULL);
  printf("main_held %d\n", main_saw == 0);

  printf("done\n");
  return 0;
}
