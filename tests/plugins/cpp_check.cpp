/* C++ runtime under syscall interposition (ref src/test/cpp parity):
 * libstdc++ static init, exceptions, std::string/iostream,
 * std::thread (pthread_create -> clone, trapped), and
 * std::chrono::steady_clock + sleep_for riding the VIRTUAL clock. */
#include <chrono>
#include <cstdio>
#include <stdexcept>
#include <string>
#include <thread>

int main() {
  std::string s = "cpp";
  try {
    throw std::runtime_error("boom");
  } catch (const std::exception &) {
    s += "-eh";
  }
  auto t0 = std::chrono::steady_clock::now();
  long got = 0;
  std::thread th([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    got = 42;
  });
  th.join();
  auto el_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                   std::chrono::steady_clock::now() - t0)
                   .count();
  std::printf("str %s\n", s.c_str());
  std::printf("thread %ld\n", got);
  std::printf("sleep_visible %d\n", el_ms >= 20 ? 1 : 0);
  std::printf("done\n");
  return 0;
}
