/* Readiness-API family under interposition (ref src/test/{epoll,
 * poll, eventfd, timerfd, pipe} suites): pipe2 + poll, eventfd
 * semantics, timerfd through epoll with EXACT virtual-time
 * advancement, and a select() timeout that consumes exactly its
 * simulated duration. Prints "label value" lines; the harness
 * asserts exact output (clocks are virtual, so output is a pure
 * function of the config). */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <stdio.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/select.h>
#include <sys/timerfd.h>
#include <time.h>
#include <unistd.h>

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000L + ts.tv_nsec / 1000000L;
}

static void check(const char *label, int ok) {
  printf("%s %d\n", label, ok);
}

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0);
  /* -- pipe2 + poll readiness -- */
  int pfd[2];
  check("pipe2", pipe2(pfd, O_NONBLOCK) == 0);
  struct pollfd pp = {.fd = pfd[0], .events = POLLIN};
  check("poll_empty", poll(&pp, 1, 0) == 0);
  check("pipe_write", write(pfd[1], "xy", 2) == 2);
  pp.revents = 0;
  check("poll_ready", poll(&pp, 1, 0) == 1 && (pp.revents & POLLIN));
  char buf[8] = {0};
  check("pipe_read", read(pfd[0], buf, 8) == 2 && !strcmp(buf, "xy"));
  check("pipe_drained", read(pfd[0], buf, 8) == -1 && errno == EAGAIN);

  /* -- eventfd counter semantics -- */
  int efd = eventfd(0, EFD_NONBLOCK);
  check("eventfd", efd >= 0);
  unsigned long v = 3;
  check("efd_write", write(efd, &v, 8) == 8);
  v = 2;
  check("efd_write2", write(efd, &v, 8) == 8);
  v = 0;
  check("efd_read", read(efd, &v, 8) == 8 && v == 5);  /* sums */
  check("efd_empty", read(efd, &v, 8) == -1 && errno == EAGAIN);

  /* -- timerfd through epoll: exact virtual-time fire -- */
  int tfd = timerfd_create(CLOCK_MONOTONIC, 0);
  check("timerfd", tfd >= 0);
  int ep = epoll_create1(0);
  check("epoll_create", ep >= 0);
  struct epoll_event ev = {.events = EPOLLIN, .data.fd = tfd};
  check("epoll_ctl", epoll_ctl(ep, EPOLL_CTL_ADD, tfd, &ev) == 0);
  struct itimerspec its = {.it_value = {0, 30 * 1000 * 1000}};
  long t0 = now_ms();
  check("tfd_arm", timerfd_settime(tfd, 0, &its, NULL) == 0);
  struct epoll_event got;
  int n = epoll_wait(ep, &got, 1, 1000);
  long waited = now_ms() - t0;
  check("epoll_fire", n == 1 && got.data.fd == tfd);
  unsigned long exp = 0;
  check("tfd_count", read(tfd, &exp, 8) == 8 && exp == 1);
  printf("tfd_wait_ms %ld\n", waited);   /* exactly 30 (virtual) */

  /* -- select() pure timeout consumes exactly its duration -- */
  fd_set rf;
  FD_ZERO(&rf);
  FD_SET(pfd[0], &rf);
  struct timeval tv = {0, 20 * 1000};
  t0 = now_ms();
  int sn = select(pfd[0] + 1, &rf, NULL, NULL, &tv);
  long slept = now_ms() - t0;
  check("select_timeout", sn == 0);
  printf("select_ms %ld\n", slept);      /* exactly 20 (virtual) */

  /* -- select readiness on a virtual fd (possible at all because
   * virtual fds live below FD_SETSIZE) -- */
  check("pipe_rewrite", write(pfd[1], "z", 1) == 1);
  FD_ZERO(&rf);
  FD_SET(pfd[0], &rf);
  fd_set wf;
  FD_ZERO(&wf);
  FD_SET(pfd[1], &wf);
  tv.tv_sec = 1;
  tv.tv_usec = 0;
  sn = select((pfd[0] > pfd[1] ? pfd[0] : pfd[1]) + 1, &rf, &wf,
              NULL, &tv);
  check("select_ready",
        sn == 2 && FD_ISSET(pfd[0], &rf) && FD_ISSET(pfd[1], &wf));
  check("pipe_rez", read(pfd[0], buf, 8) == 1);

  /* -- epoll sees the eventfd too -- */
  ev.events = EPOLLIN;
  ev.data.fd = efd;
  check("epoll_ctl2", epoll_ctl(ep, EPOLL_CTL_ADD, efd, &ev) == 0);
  v = 7;
  check("efd_rewrite", write(efd, &v, 8) == 8);
  n = epoll_wait(ep, &got, 1, 0);
  check("epoll_efd", n == 1 && got.data.fd == efd);

  close(ep);
  close(tfd);
  close(efd);
  close(pfd[0]);
  close(pfd[1]);
  printf("done\n");
  return 0;
}
