/* UDP echo server: echoes `count` datagrams then exits.
 * The managed-process analogue of the reference's src/test/udp suite. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: udp_echo <port> <count>\n");
    return 2;
  }
  int port = atoi(argv[1]);
  int count = atoi(argv[2]);

  int s = socket(AF_INET, SOCK_DGRAM, 0);
  if (s < 0) {
    perror("socket");
    return 1;
  }
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  a.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind(s, (struct sockaddr *)&a, sizeof a) != 0) {
    perror("bind");
    return 1;
  }
  char buf[2048];
  for (int i = 0; i < count; i++) {
    struct sockaddr_in src;
    socklen_t sl = sizeof src;
    ssize_t r = recvfrom(s, buf, sizeof buf, 0, (struct sockaddr *)&src,
                         &sl);
    if (r < 0) {
      perror("recvfrom");
      return 1;
    }
    if (sendto(s, buf, (size_t)r, 0, (struct sockaddr *)&src, sl) != r) {
      perror("sendto");
      return 1;
    }
    printf("echoed %zd from %s:%d\n", r, inet_ntoa(src.sin_addr),
           ntohs(src.sin_port));
  }
  close(s);
  printf("done\n");
  fflush(stdout);
  return 0;
}
