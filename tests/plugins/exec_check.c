/* execve under interposition: a managed process execs a second
 * program (the fork-exec pattern real launchers use) and the new
 * image stays managed — same virtual pid, continuous simulated time,
 * exit status visible to wait4. Also: exec of a missing path fails
 * with ENOENT and the OLD image continues, and close-on-exec virtual
 * descriptors don't survive into the new image. */
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

extern char **environ;

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(int argc, char **argv) {
  setvbuf(stdout, NULL, _IONBF, 0);
  if (argc < 2) {
    printf("usage: exec_check <target>\n");
    return 1;
  }

  /* 1: exec of a missing path fails, old image continues */
  char *bad[] = {"nope", NULL};
  int r = execve("/does/not/exist", bad, environ);
  printf("badexec %d errno_ok %d\n", r == -1, errno == ENOENT);

  /* 2: fork + exec; child keeps its vpid across the exec and its
   * simulated clock keeps running; parent reaps exit code 33. The
   * child takes two virtual sockets into the exec: one marked
   * FD_CLOEXEC (must be closed in the new image) and one not (must
   * survive) — the target probes both by fd number. */
  int keep = socket(AF_INET, SOCK_DGRAM, 0);
  int gone = socket(AF_INET, SOCK_DGRAM, 0);
  fcntl(gone, F_SETFD, FD_CLOEXEC);
  long t0 = now_ms();
  pid_t child = fork();
  if (child == 0) {
    printf("child pre-exec pid %d t_ms %ld\n", (int)getpid(),
           now_ms() - t0);
    usleep(40 * 1000);                   /* 40 ms before the exec */
    char fd_keep[16], fd_gone[16];
    snprintf(fd_keep, sizeof fd_keep, "%d", keep);
    snprintf(fd_gone, sizeof fd_gone, "%d", gone);
    char *args[] = {"exec_target", "hello", fd_keep, fd_gone, NULL};
    execve(argv[1], args, environ);
    printf("exec failed errno %d\n", errno);
    _exit(9);
  }
  int st = 0;
  pid_t w = waitpid(child, &st, 0);
  long dt = now_ms() - t0;
  printf("reap ok %d exited %d code %d t_ms %ld\n",
         w == child, WIFEXITED(st), WEXITSTATUS(st), dt);
  close(keep);
  close(gone);
  printf("done\n");
  return 0;
}
