/* Deterministic randomness: getrandom(2) and the shim's OpenSSL
 * RAND_bytes override (openssl_preload analogue) must both draw from
 * the simulator's seeded per-host stream — identical across runs of
 * the same seed. RAND_bytes is resolved with dlsym(RTLD_DEFAULT): no
 * libcrypto dev files in the image, and under the simulator the
 * LD_PRELOADed shim provides the symbol exactly like it would shadow
 * a real libcrypto's. */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <stdio.h>
#include <sys/random.h>

static void hex(const char *tag, const unsigned char *b, int n) {
  printf("%s ", tag);
  for (int i = 0; i < n; i++)
    printf("%02x", b[i]);
  printf("\n");
}

int main(void) {
  unsigned char a[8], b[8];
  if (getrandom(a, sizeof a, 0) != (long)sizeof a) {
    perror("getrandom");
    return 1;
  }
  hex("getrandom", a, sizeof a);
  int (*rand_bytes)(unsigned char *, int) =
      (int (*)(unsigned char *, int))dlsym(RTLD_DEFAULT, "RAND_bytes");
  if (!rand_bytes) {
    printf("randbytes unavailable\n");
    return 0;
  }
  if (rand_bytes(b, sizeof b) != 1) {
    fprintf(stderr, "RAND_bytes failed\n");
    return 1;
  }
  hex("randbytes", b, sizeof b);
  return 0;
}
