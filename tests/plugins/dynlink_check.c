/* Runtime dynamic linking under interposition (ref src/test/dynlink
 * parity): dlopen a shared object, resolve symbols, and verify the
 * dlopened code shares the main image's virtual timeline. */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <stdio.h>
#include <time.h>

int main(int argc, char **argv) {
  if (argc < 2) {
    printf("no lib\n");
    return 1;
  }
  void *h = dlopen(argv[1], RTLD_NOW);
  printf("dlopen %d\n", h != NULL);
  if (!h) {
    printf("%s\n", dlerror());
    return 1;
  }
  long (*add)(long, long) = (long (*)(long, long))dlsym(h, "dyn_add");
  long (*now)(void) = (long (*)(void))dlsym(h, "dyn_now_ns");
  printf("dlsym %d\n", add != NULL && now != NULL);
  printf("add %ld\n", add(40, 2));

  long a = now(); /* read via the dlopened library */
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts); /* read via the main image */
  long b = ts.tv_sec * 1000000000L + ts.tv_nsec;
  struct timespec d = {0, 5 * 1000 * 1000};
  nanosleep(&d, 0);
  long c = now();
  printf("monotonic %d\n", b >= a);
  printf("sleep_visible %d\n", c >= b + 5 * 1000 * 1000);
  printf("done\n");
  return 0;
}
