/* Name resolution inside the simulation: gethostname, getaddrinfo on
 * simulated hostnames (shim overrides backed by the simulator's hosts
 * file), getifaddrs, and a by-NAME TCP connect to prove the resolved
 * address actually routes. */
#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  const char *peer = argc > 1 ? argv[1] : "server";
  int port = argc > 2 ? atoi(argv[2]) : 8080;

  char hn[256];
  if (gethostname(hn, sizeof hn) != 0) {
    perror("gethostname");
    return 1;
  }
  printf("hostname %s\n", hn);

  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  struct addrinfo *res = NULL;
  int rc = getaddrinfo(peer, portbuf, &hints, &res);
  if (rc != 0) {
    printf("getaddrinfo(%s) rc=%d\n", peer, rc);
    return 1;
  }
  struct sockaddr_in *sa = (struct sockaddr_in *)res->ai_addr;
  printf("resolved %s %s:%d\n", peer, inet_ntoa(sa->sin_addr),
         ntohs(sa->sin_port));

  /* unknown name must fail cleanly */
  struct addrinfo *none = NULL;
  rc = getaddrinfo("no-such-host-xyz", NULL, &hints, &none);
  printf("unknown rc==EAI_NONAME %d\n", rc == EAI_NONAME);

  /* own name resolves to own address */
  struct addrinfo *self = NULL;
  if (getaddrinfo(hn, NULL, &hints, &self) == 0) {
    struct sockaddr_in *me = (struct sockaddr_in *)self->ai_addr;
    printf("self %s\n", inet_ntoa(me->sin_addr));
    freeaddrinfo(self);
  }

  struct ifaddrs *ifa = NULL;
  if (getifaddrs(&ifa) == 0) {
    for (struct ifaddrs *p = ifa; p; p = p->ifa_next) {
      struct sockaddr_in *a = (struct sockaddr_in *)p->ifa_addr;
      printf("if %s %s\n", p->ifa_name, inet_ntoa(a->sin_addr));
    }
    freeifaddrs(ifa);
  }

  /* connect BY NAME and stream a little data */
  int s = socket(AF_INET, SOCK_STREAM, 0);
  if (connect(s, res->ai_addr, res->ai_addrlen) != 0) {
    perror("connect");
    return 1;
  }
  const char msg[] = "hello-by-name";
  long w = write(s, msg, sizeof msg - 1);
  printf("connected wrote %ld\n", w);
  close(s);
  freeaddrinfo(res);
  return 0;
}
