/* TCP sink server: accepts one connection, reads until EOF, prints
 * byte count + checksum. The managed analogue of src/test/tcp. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: tcp_server <port>\n");
    return 2;
  }
  int port = atoi(argv[1]);
  int s = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  a.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind(s, (struct sockaddr *)&a, sizeof a) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(s, 8) != 0) {
    perror("listen");
    return 1;
  }
  struct sockaddr_in peer;
  socklen_t pl = sizeof peer;
  int c = accept(s, (struct sockaddr *)&peer, &pl);
  if (c < 0) {
    perror("accept");
    return 1;
  }
  printf("accepted from %s:%d\n", inet_ntoa(peer.sin_addr),
         ntohs(peer.sin_port));
  unsigned long total = 0, sum = 0;
  char buf[16384];
  for (;;) {
    ssize_t r = read(c, buf, sizeof buf);
    if (r < 0) {
      perror("read");
      return 1;
    }
    if (r == 0)
      break;
    for (ssize_t i = 0; i < r; i++)
      sum = (sum * 31 + (unsigned char)buf[i]) & 0xFFFFFFFFUL;
    total += (unsigned long)r;
  }
  printf("received %lu bytes sum %lu\n", total, sum);
  close(c);
  close(s);
  fflush(stdout);
  return 0;
}
