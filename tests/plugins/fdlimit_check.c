/* Virtual fd window semantics: [600, 1024) = 424 slots. Exhaustion
 * answers EMFILE exactly at capacity (no state leaked by the failed
 * call), closing recycles slots, and allocation is kernel-style
 * lowest-free within the window. */
#define _GNU_SOURCE
#include <errno.h>
#include <sys/resource.h>
#include <stdio.h>
#include <unistd.h>

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0);
  int fds[1024];
  int n = 0;
  int saw_emfile = 0;
  while (n < 1000) {
    int p[2];
    if (pipe(p) < 0) {
      saw_emfile = (errno == EMFILE);
      break;
    }
    fds[n++] = p[0];
    fds[n++] = p[1];
  }
  printf("emfile %d\n", saw_emfile);
  printf("capacity %d\n", n);           /* exactly 424 */
  /* the first fd is the window floor, allocated lowest-first */
  printf("floor %d\n", n > 0 ? fds[0] : -1);

  /* recycle: close two in the MIDDLE, reopen — lowest-free reuses
   * exactly those slots */
  int a = fds[10], b = fds[11];
  close(a);
  close(b);
  int p2[2];
  printf("reopen %d\n", pipe(p2) == 0);
  printf("lowest_free %d\n",
         (p2[0] == (a < b ? a : b)) && (p2[1] == (a < b ? b : a)));

  /* full close -> full capacity again */
  for (int i = 0; i < n; i++)
    if (fds[i] != a && fds[i] != b) close(fds[i]);
  close(p2[0]);
  close(p2[1]);
  int p3[2];
  printf("drain_reopen %d\n", pipe(p3) == 0 && p3[0] == 600);

  /* libc callers see VIRTUAL rlimits (default 1024/1M) even though
   * the spawn path capped the NATIVE limit at 600 */
  struct rlimit rl;
  printf("rlimit_virtual_default %d\n",
         getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur == 1024);
  struct rlimit nl = {512, 2048};
  printf("setrlimit %d\n", setrlimit(RLIMIT_NOFILE, &nl) == 0);
  printf("rlimit_roundtrip %d\n",
         getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur == 512 &&
         rl.rlim_max == 2048);
  printf("done\n");
  return 0;
}
