/* Scripted UDP sender for recvmmsg_check: two datagrams back-to-back,
 * then one after 300 ms, then one after a further 500 ms. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: udp_burst <ip> <port>\n");
    return 2;
  }
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in d;
  memset(&d, 0, sizeof d);
  d.sin_family = AF_INET;
  d.sin_port = htons(atoi(argv[2]));
  d.sin_addr.s_addr = inet_addr(argv[1]);
  const struct sockaddr *da = (const struct sockaddr *)&d;
  sendto(s, "d1", 2, 0, da, sizeof d);
  sendto(s, "d2", 2, 0, da, sizeof d);
  usleep(300 * 1000);
  sendto(s, "d3", 2, 0, da, sizeof d);
  usleep(500 * 1000);
  sendto(s, "d4", 2, 0, da, sizeof d);
  printf("sent 4\n");
  return 0;
}
