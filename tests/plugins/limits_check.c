/* Deterministic resource limits + minimal prctl virtualization:
 * getrlimit must report the SIMULATED fixed machine (never the real
 * one), setrlimit must round-trip, and PR_SET_NAME / PR_SET_PDEATHSIG
 * must be visible through their getters. */
#define _GNU_SOURCE
#include <stdio.h>
#include <string.h>
#include <sys/prctl.h>
#include <sys/resource.h>
#include <sys/time.h>

int main(void) {
  struct rlimit rl;
  if (getrlimit(RLIMIT_NOFILE, &rl) != 0) { perror("getrlimit"); return 1; }
  printf("nofile %llu %llu\n", (unsigned long long)rl.rlim_cur,
         (unsigned long long)rl.rlim_max);
  rl.rlim_cur = 512;
  printf("setrlimit %d\n", setrlimit(RLIMIT_NOFILE, &rl));
  if (getrlimit(RLIMIT_NOFILE, &rl) != 0) return 1;
  printf("nofile2 %llu %llu\n", (unsigned long long)rl.rlim_cur,
         (unsigned long long)rl.rlim_max);
  if (getrlimit(RLIMIT_STACK, &rl) != 0) return 1;
  printf("stack_soft %llu\n", (unsigned long long)rl.rlim_cur);

  if (prctl(PR_SET_PDEATHSIG, 15) != 0) { perror("pdeathsig"); return 1; }
  int sig = 0;
  prctl(PR_GET_PDEATHSIG, &sig);
  printf("pdeathsig %d\n", sig);

  prctl(PR_SET_NAME, "worker0");
  char name[17] = {0};
  prctl(PR_GET_NAME, name);
  printf("name %s\n", name);
  printf("done\n");
  return 0;
}
