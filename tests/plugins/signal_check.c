/* Virtual signal delivery: self-kill runs the handler at the syscall
 * boundary; a forked child's signal interrupts the parent's blocking
 * nanosleep with EINTR at the simulated send instant; SIG_IGN and
 * default-ignore signals are inert. */
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t got1 = 0, got2 = 0;
static volatile long h1_time = -1;

static long now_ms(void);

/* the handler itself makes a TRAPPED syscall (clock_gettime goes
 * through the shim funnel): delivery must service it */
static void h1(int sig) {
  got1 = sig;
  h1_time = now_ms();
}
static void h2(int sig, siginfo_t *si, void *uc) {
  (void)uc;
  got2 = sig + (si != NULL);
}

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(void) {
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = h1;
  sigaction(SIGUSR1, &sa, NULL);

  struct sigaction sa2;
  memset(&sa2, 0, sizeof sa2);
  sa2.sa_sigaction = h2;
  sa2.sa_flags = SA_SIGINFO;
  sigaction(SIGUSR2, &sa2, NULL);

  /* self-signal: handler runs before kill() returns to us, and the
   * handler's own trapped clock_gettime works */
  kill(getpid(), SIGUSR1);
  printf("self got1 %d handler_syscall_ok %d\n", (int)got1,
         h1_time >= 0);

  /* ignored signal is inert */
  signal(SIGHUP, SIG_IGN);
  kill(getpid(), SIGHUP);
  printf("ignored ok\n");

  /* cross-process: child interrupts parent's 10 s nanosleep at 150 ms */
  long t0 = now_ms();
  pid_t child = fork();
  if (child == 0) {
    usleep(150 * 1000);
    kill(getppid(), SIGUSR2);
    _exit(0);
  }
  struct timespec req = {10, 0};
  int r = nanosleep(&req, NULL);
  long dt = now_ms() - t0;
  printf("eintr %d errno_ok %d got2 %d t_ms %ld\n", r == -1,
         errno == EINTR, (int)got2, dt);
  int st;
  waitpid(child, &st, 0);

  /* SIGKILL a sleeping child: wait status must say SIGNALED(9) */
  long tk = now_ms();
  pid_t victim = fork();
  if (victim == 0) {
    sleep(10);
    _exit(0);
  }
  usleep(50 * 1000);
  kill(victim, SIGKILL);
  int vst = 0;
  pid_t vr = waitpid(victim, &vst, 0);
  printf("sigkill ok %d signaled %d sig %d t_ms %ld\n", vr == victim,
         WIFSIGNALED(vst), WTERMSIG(vst), now_ms() - tk);
  printf("done\n");
  return 0;
}
