/* Multi-hop relay: accepts a connection, reads a one-line forwarding
 * header "IP PORT\n", connects onward, and pipes the remaining bytes
 * downstream until EOF. Chained three deep this is the honest Tor
 * analogue (ref src/test/tor runs the real tor binary): REAL
 * processes forwarding through the EMULATED TCP stack, not an
 * idealized circuit model. args: <listen_port> [circuits] */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static int read_line(int fd, char *buf, int cap) {
  int n = 0;
  while (n < cap - 1) {
    ssize_t r = read(fd, buf + n, 1);
    if (r <= 0) return -1;
    if (buf[n] == '\n') { buf[n] = 0; return n; }
    n++;
  }
  return -1;
}

int main(int argc, char **argv) {
  if (argc < 2) { fprintf(stderr, "usage: relay <port> [circuits]\n"); return 2; }
  int port = atoi(argv[1]);
  int circuits = argc > 2 ? atoi(argv[2]) : 1;
  int s = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  a.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind(s, (struct sockaddr *)&a, sizeof a) != 0) { perror("bind"); return 1; }
  if (listen(s, 8) != 0) { perror("listen"); return 1; }
  for (int c = 0; c < circuits; c++) {
    int up = accept(s, NULL, NULL);
    if (up < 0) { perror("accept"); return 1; }
    char hdr[128];
    if (read_line(up, hdr, sizeof hdr) < 0) { close(up); continue; }
    char ip[64]; int nport;
    if (sscanf(hdr, "%63s %d", ip, &nport) != 2) { close(up); continue; }
    int down = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in d;
    memset(&d, 0, sizeof d);
    d.sin_family = AF_INET;
    d.sin_port = htons(nport);
    d.sin_addr.s_addr = inet_addr(ip);
    if (connect(down, (struct sockaddr *)&d, sizeof d) != 0) {
      perror("connect"); close(up); close(down); continue;
    }
    unsigned long fwd = 0;
    char buf[8192];
    for (;;) {
      ssize_t r = read(up, buf, sizeof buf);
      if (r <= 0) break;
      long off = 0;
      while (off < r) {
        ssize_t w = write(down, buf + off, (size_t)(r - off));
        if (w < 0) { perror("write"); return 1; }
        off += w;
      }
      fwd += (unsigned long)r;
    }
    close(up);
    close(down);          /* EOF propagates down the chain */
    printf("circuit %d forwarded %lu\n", c, fwd);
  }
  close(s);
  fflush(stdout);
  return 0;
}
