/* dlopen target (built as a .so by the test fixture): proves symbols
 * resolve at runtime and that code in a dlopened library sees the
 * SAME virtual clock as the main image — seccomp interposition is
 * process-wide and the preload overrides bind into the .so's PLT. */
#include <time.h>

long dyn_add(long a, long b) { return a + b; }

long dyn_now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000L + ts.tv_nsec;
}
