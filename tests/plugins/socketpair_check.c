/* socketpair(AF_UNIX) under interposition (ref dispatch `socketpair`
 * parity): the classic privilege-separation pattern — a STREAM pair
 * shared across fork() with bidirectional messages and EOF on peer
 * close, plus DGRAM message boundaries and shutdown semantics in one
 * process. Prints "label value" lines; clocks are virtual so output
 * is exact. */
#define _GNU_SOURCE
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <time.h>
#include <sys/ioctl.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/uio.h>
#include <sys/wait.h>
#include <unistd.h>

static void check(const char *label, int ok) {
  printf("%s %d\n", label, ok);
}

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0);
  signal(SIGPIPE, SIG_IGN); /* EPIPE as errno, both worlds */

  /* -- DGRAM pair keeps message boundaries -- */
  int dg[2];
  check("dgram_pair",
        socketpair(AF_UNIX, SOCK_DGRAM, 0, dg) == 0);
  check("dg_send1", send(dg[0], "one", 3, 0) == 3);
  check("dg_send2", send(dg[0], "twotwo", 6, 0) == 6);
  char buf[64] = {0};
  check("dg_recv1", recv(dg[1], buf, 64, 0) == 3 &&
        !memcmp(buf, "one", 3));
  check("dg_recv2", recv(dg[1], buf, 64, 0) == 6 &&
        !memcmp(buf, "twotwo", 6));
  close(dg[0]);
  close(dg[1]);

  /* -- SO_TYPE reflects the pair flavor; FIONREAD counts -- */
  int dg2[2];
  check("dg2_pair", socketpair(AF_UNIX, SOCK_DGRAM, 0, dg2) == 0);
  int sotype = 0;
  socklen_t slen = sizeof sotype;
  check("so_type_dgram",
        getsockopt(dg2[0], SOL_SOCKET, SO_TYPE, &sotype, &slen) == 0
        && sotype == SOCK_DGRAM);
  check("dg2_send", send(dg2[0], "abcd", 4, 0) == 4);
  int avail = -1;
  check("fionread", ioctl(dg2[1], FIONREAD, &avail) == 0 &&
        avail == 4);
  struct sockaddr_un su;
  socklen_t sulen = sizeof su;
  check("getsockname_unnamed",
        getsockname(dg2[0], (struct sockaddr *)&su, &sulen) == 0 &&
        sulen == 2 && su.sun_family == AF_UNIX);
  close(dg2[0]);
  close(dg2[1]);

  /* -- sendmsg/recvmsg gather/scatter on a stream pair -- */
  int sm[2];
  check("sm_pair", socketpair(AF_UNIX, SOCK_STREAM, 0, sm) == 0);
  struct iovec siov[2] = {{"hel", 3}, {"lo!", 3}};
  struct msghdr mh;
  memset(&mh, 0, sizeof mh);
  mh.msg_iov = siov;
  mh.msg_iovlen = 2;
  check("sendmsg", sendmsg(sm[0], &mh, 0) == 6);
  char r1[4] = {0}, r2[4] = {0};
  struct iovec riov[2] = {{r1, 3}, {r2, 3}};
  memset(&mh, 0, sizeof mh);
  mh.msg_iov = riov;
  mh.msg_iovlen = 2;
  check("recvmsg", recvmsg(sm[1], &mh, 0) >= 3 &&
        !memcmp(r1, "hel", 3));
  close(sm[0]);
  close(sm[1]);

  /* -- MSG_PEEK leaves the data in place -- */
  int pk[2];
  check("peek_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, pk) == 0);
  check("peek_send", send(pk[0], "abc", 3, 0) == 3);
  check("peek", recv(pk[1], buf, 64, MSG_PEEK) == 3 &&
        !memcmp(buf, "abc", 3));
  check("peek_consume", recv(pk[1], buf, 64, 0) == 3);
  close(pk[0]);
  close(pk[1]);

  /* -- STREAM pair across fork: request/reply, then EOF -- */
  int sv[2];
  check("stream_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  pid_t pid = fork();
  if (pid == 0) {
    /* child: serve one request, reply after 50 ms (forces the
     * parent's read to PARK and be woken), linger 50 ms more before
     * exiting (forces the parent's EOF read to park on the close
     * path too) */
    close(sv[0]);
    char req[64] = {0};
    ssize_t r = read(sv[1], req, 64);
    struct timespec d = {0, 50 * 1000 * 1000};
    nanosleep(&d, 0);
    if (r > 0 && !strcmp(req, "ping")) {
      write(sv[1], "pong", 5);
    }
    nanosleep(&d, 0);
    close(sv[1]);
    _exit(0);
  }
  check("fork", pid > 0);
  close(sv[1]);
  check("req", write(sv[0], "ping", 5) == 5);
  memset(buf, 0, sizeof buf);
  check("reply", read(sv[0], buf, 64) == 5 && !strcmp(buf, "pong"));
  /* child closed its end: next read sees EOF */
  check("eof", read(sv[0], buf, 64) == 0);
  int st = -1;
  check("wait", waitpid(pid, &st, 0) == pid && WIFEXITED(st) &&
        WEXITSTATUS(st) == 0);

  /* -- shutdown(SHUT_WR) gives the peer EOF; writes then EPIPE -- */
  int sh[2];
  check("shut_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, sh) == 0);
  check("shut_wr", shutdown(sh[0], SHUT_WR) == 0);
  check("shut_eof", read(sh[1], buf, 64) == 0);
  check("shut_epipe",
        write(sh[0], "x", 1) == -1 && errno == EPIPE);
  check("shut_other_way", write(sh[1], "y", 1) == 1);
  check("shut_still_reads", read(sh[0], buf, 1) == 1 &&
        buf[0] == 'y');
  close(sh[0]);
  close(sh[1]);

  /* -- a blocking stream write LARGER than the 64 KiB buffer must
   * complete fully (Linux unix_stream_sendmsg blocks until queued;
   * a short return would silently lose the tail) -- */
  int bw[2];
  check("bw_pair", socketpair(AF_UNIX, SOCK_STREAM, 0, bw) == 0);
  pid_t dr = fork();
  if (dr == 0) {
    close(bw[0]);
    char sink[8192];
    long total = 0;
    struct timespec nap = {0, 2 * 1000 * 1000};
    while (total < 100000) {
      nanosleep(&nap, 0);             /* slow drain forces blocking */
      ssize_t r = read(bw[1], sink, sizeof sink);
      if (r <= 0) break;
      total += r;
    }
    _exit(total == 100000 ? 0 : 1);
  }
  close(bw[1]);
  static char big[100000];
  memset(big, 'Q', sizeof big);
  check("big_write_full", write(bw[0], big, sizeof big) ==
        (ssize_t)sizeof big);
  close(bw[0]);
  int bst = -1;
  check("drain_ok", waitpid(dr, &bst, 0) == dr && WIFEXITED(bst) &&
        WEXITSTATUS(bst) == 0);
  printf("done\n");
  return 0;
}
