/* socketpair(AF_UNIX) under interposition (ref dispatch `socketpair`
 * parity): the classic privilege-separation pattern — a STREAM pair
 * shared across fork() with bidirectional messages and EOF on peer
 * close, plus DGRAM message boundaries and shutdown semantics in one
 * process. Prints "label value" lines; clocks are virtual so output
 * is exact. */
#define _GNU_SOURCE
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <time.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

static void check(const char *label, int ok) {
  printf("%s %d\n", label, ok);
}

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0);
  signal(SIGPIPE, SIG_IGN); /* EPIPE as errno, both worlds */

  /* -- DGRAM pair keeps message boundaries -- */
  int dg[2];
  check("dgram_pair",
        socketpair(AF_UNIX, SOCK_DGRAM, 0, dg) == 0);
  check("dg_send1", send(dg[0], "one", 3, 0) == 3);
  check("dg_send2", send(dg[0], "twotwo", 6, 0) == 6);
  char buf[64] = {0};
  check("dg_recv1", recv(dg[1], buf, 64, 0) == 3 &&
        !memcmp(buf, "one", 3));
  check("dg_recv2", recv(dg[1], buf, 64, 0) == 6 &&
        !memcmp(buf, "twotwo", 6));
  close(dg[0]);
  close(dg[1]);

  /* -- MSG_PEEK leaves the data in place -- */
  int pk[2];
  check("peek_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, pk) == 0);
  check("peek_send", send(pk[0], "abc", 3, 0) == 3);
  check("peek", recv(pk[1], buf, 64, MSG_PEEK) == 3 &&
        !memcmp(buf, "abc", 3));
  check("peek_consume", recv(pk[1], buf, 64, 0) == 3);
  close(pk[0]);
  close(pk[1]);

  /* -- STREAM pair across fork: request/reply, then EOF -- */
  int sv[2];
  check("stream_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  pid_t pid = fork();
  if (pid == 0) {
    /* child: serve one request, reply after 50 ms (forces the
     * parent's read to PARK and be woken), linger 50 ms more before
     * exiting (forces the parent's EOF read to park on the close
     * path too) */
    close(sv[0]);
    char req[64] = {0};
    ssize_t r = read(sv[1], req, 64);
    struct timespec d = {0, 50 * 1000 * 1000};
    nanosleep(&d, 0);
    if (r > 0 && !strcmp(req, "ping")) {
      write(sv[1], "pong", 5);
    }
    nanosleep(&d, 0);
    close(sv[1]);
    _exit(0);
  }
  check("fork", pid > 0);
  close(sv[1]);
  check("req", write(sv[0], "ping", 5) == 5);
  memset(buf, 0, sizeof buf);
  check("reply", read(sv[0], buf, 64) == 5 && !strcmp(buf, "pong"));
  /* child closed its end: next read sees EOF */
  check("eof", read(sv[0], buf, 64) == 0);
  int st = -1;
  check("wait", waitpid(pid, &st, 0) == pid && WIFEXITED(st) &&
        WEXITSTATUS(st) == 0);

  /* -- shutdown(SHUT_WR) gives the peer EOF; writes then EPIPE -- */
  int sh[2];
  check("shut_pair",
        socketpair(AF_UNIX, SOCK_STREAM, 0, sh) == 0);
  check("shut_wr", shutdown(sh[0], SHUT_WR) == 0);
  check("shut_eof", read(sh[1], buf, 64) == 0);
  check("shut_epipe",
        write(sh[0], "x", 1) == -1 && errno == EPIPE);
  check("shut_other_way", write(sh[1], "y", 1) == 1);
  check("shut_still_reads", read(sh[0], buf, 1) == 1 &&
        buf[0] == 'y');
  close(sh[0]);
  close(sh[1]);
  printf("done\n");
  return 0;
}
