/* Raw clone3 (no glibc fallback): musl/Go issue clone3 natively.
 * Thread flavor via inline asm (the child lands on the fresh stack,
 * calls fn, exits raw), fork flavor via the syscall() wrapper.
 * Validates: struct clone_args parsing, virtual tid rewrite
 * (CHILD_SETTID word), CLEARTID futex wake on thread death, and
 * clone3-fork with wait4. */
#define _GNU_SOURCE
#include <linux/sched.h>
#include <linux/futex.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/syscall.h>
#include <sys/wait.h>
#include <unistd.h>

static int child_tid_word;

static void child_fn(void) {
  const char msg[] = "t-child ran\n";
  syscall(SYS_write, 1, msg, sizeof msg - 1);
}

static long clone3_thread(struct clone_args *cl, void (*fn)(void)) {
  long ret;
  __asm__ volatile(
      "syscall\n\t"
      "test %%rax, %%rax\n\t"
      "jnz 1f\n\t"
      "call *%[fn]\n\t"
      "mov $60, %%rax\n\t"
      "xor %%edi, %%edi\n\t"
      "syscall\n\t"
      "1:"
      : "=a"(ret)
      : "a"(SYS_clone3), "D"(cl), "S"(sizeof *cl), [fn] "r"(fn)
      : "rcx", "r11", "memory");
  return ret;
}

static char tstack[65536] __attribute__((aligned(16)));

int main(void) {
  struct clone_args cl;
  memset(&cl, 0, sizeof cl);
  cl.flags = CLONE_VM | CLONE_FS | CLONE_FILES | CLONE_SIGHAND |
             CLONE_THREAD | CLONE_SYSVSEM | CLONE_CHILD_SETTID |
             CLONE_CHILD_CLEARTID;
  cl.stack = (uint64_t)(uintptr_t)tstack;
  cl.stack_size = sizeof tstack;
  cl.child_tid = (uint64_t)(uintptr_t)&child_tid_word;
  child_tid_word = -1;
  long vtid = clone3_thread(&cl, child_fn);
  if (vtid < 0) {
    printf("clone3 thread failed %ld\n", vtid);
    return 1;
  }
  /* CHILD_SETTID poked the VIRTUAL tid; CLEARTID zeroes it at death
   * (futex-wake through the emulated table) */
  while (__atomic_load_n(&child_tid_word, __ATOMIC_SEQ_CST) != 0)
    syscall(SYS_futex, &child_tid_word, FUTEX_WAIT, vtid, NULL, 0, 0);
  printf("thread vtid_delta=%ld cleared=%d\n",
         vtid - (long)getpid(), child_tid_word == 0);

  /* fork flavor: empty args + SIGCHLD */
  memset(&cl, 0, sizeof cl);
  cl.exit_signal = SIGCHLD;
  long pid = syscall(SYS_clone3, &cl, sizeof cl);
  if (pid == 0) {
    printf("f-child pid_delta=%ld\n", (long)getpid() - (long)getppid());
    fflush(stdout);
    _exit(7);
  }
  int st = 0;
  waitpid((pid_t)pid, &st, 0);
  printf("fork rc=%ld exited=%d code=%d\n", pid > 0 ? 1L : 0L,
         WIFEXITED(st), WEXITSTATUS(st));
  printf("done\n");
  return 0;
}
