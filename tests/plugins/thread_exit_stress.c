/* Joiner-vs-exit stress: threads exit the instant they start while the
 * main thread joins immediately — maximizing pressure on the window
 * between a thread's exit syscall and its real death, where waking the
 * joiner early lets glibc free a stack the dying thread still runs on
 * (the CLEARTID death-guard race). Each joined thread's stack is
 * immediately reused by the next create. */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>

static void *worker(void *arg) {
  return (void *)((long)arg * 3 + 1);
}

int main(int argc, char **argv) {
  int rounds = argc > 1 ? atoi(argv[1]) : 64;
  long acc = 0;
  for (int i = 0; i < rounds; i++) {
    pthread_t t;
    if (pthread_create(&t, NULL, worker, (void *)(long)i) != 0) {
      perror("pthread_create");
      return 1;
    }
    void *ret;
    if (pthread_join(t, &ret) != 0) {
      perror("pthread_join");
      return 1;
    }
    acc += (long)ret;
  }
  printf("acc %ld\n", acc);
  return 0;
}
