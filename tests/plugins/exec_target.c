/* The post-execve image: proves the new program is still managed —
 * simulated time continues from the exec instant, the virtual pid is
 * unchanged, argv made it across, and the exit code reaches wait4. */
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
  setvbuf(stdout, NULL, _IONBF, 0);
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);   /* trapped: sim time */
  long ms = ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
  printf("target pid %d argc %d arg1 %s t_ms %ld\n", (int)getpid(),
         argc, argc > 1 ? argv[1] : "-", ms);
  if (argc > 3) {
    /* inherited virtual fds: argv[2] survives, argv[3] was cloexec */
    int keep = atoi(argv[2]), gone = atoi(argv[3]);
    int keep_ok = fcntl(keep, F_GETFL) >= 0;
    int gone_ok = fcntl(gone, F_GETFL) < 0 && errno == EBADF;
    printf("cloexec keep %d gone %d\n", keep_ok, gone_ok);
  }
  usleep(70 * 1000);                     /* 70 ms of simulated sleep */
  clock_gettime(CLOCK_MONOTONIC, &ts);
  printf("target done t_ms %ld\n",
         ts.tv_sec * 1000 + ts.tv_nsec / 1000000);
  return 33;
}
