/* Deterministic resource/topology views: getrusage/times report
 * SIMULATED elapsed time, the scheduler sees ONE cpu — nothing the
 * real machine can leak through. */
#define _GNU_SOURCE
#include <sched.h>
#include <stdio.h>
#include <sys/resource.h>
#include <sys/times.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  struct timespec ts = {0, 250 * 1000 * 1000};
  nanosleep(&ts, NULL);                  /* sim t = 1.25s */
  struct rusage ru;
  if (getrusage(RUSAGE_SELF, &ru) != 0) { perror("rusage"); return 1; }
  printf("utime %ld.%06ld stime %ld\n", (long)ru.ru_utime.tv_sec,
         (long)ru.ru_utime.tv_usec, (long)ru.ru_stime.tv_sec);
  struct tms t;
  long ticks = times(&t);
  printf("ticks %ld utime_t %ld\n", ticks, (long)t.tms_utime);
  cpu_set_t cs;
  CPU_ZERO(&cs);
  if (sched_getaffinity(0, sizeof cs, &cs) != 0) {
    perror("affinity");
    return 1;
  }
  printf("ncpu %d cpu0 %d\n", CPU_COUNT(&cs), CPU_ISSET(0, &cs));
  printf("nproc_conf %ld\n", sysconf(_SC_NPROCESSORS_ONLN));
  unsigned cpu = 99, node = 99;
  getcpu(&cpu, &node);
  printf("getcpu %u %u\n", cpu, node);
  printf("done\n");
  return 0;
}
