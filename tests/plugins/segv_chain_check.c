/* App-installed SIGSEGV handlers must coexist with the shim's TSC
 * emulation: rdtsc still reads simulated time, while a REAL fault
 * chains to the app's handler (which recovers via siglongjmp). */
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <unistd.h>

static sigjmp_buf env;
static volatile int faults = 0;

static void on_segv(int sig, siginfo_t *info, void *ctx) {
  (void)sig;
  (void)info;
  (void)ctx;
  faults++;
  siglongjmp(env, 1);
}

static inline uint64_t rdtsc(void) {
  uint32_t lo, hi;
  __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
}

int main(void) {
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_sigaction = on_segv;
  sa.sa_flags = SA_SIGINFO;
  if (sigaction(SIGSEGV, &sa, NULL) != 0) {
    perror("sigaction");
    return 1;
  }

  uint64_t t0 = rdtsc();        /* must be emulated, not chained */
  usleep(20000);
  uint64_t t1 = rdtsc();
  printf("dt %llu\n", (unsigned long long)(t1 - t0));

  if (sigsetjmp(env, 1) == 0) {
    *(volatile int *)0 = 1;     /* real fault -> app handler */
    printf("not reached\n");
  }
  printf("faults %d\n", faults);

  uint64_t t2 = rdtsc();        /* emulation still live after chain */
  printf("t2_ge %d\n", t2 >= t1);
  return 0;
}
