/* rdtsc/rdtscp determinism probe for the ptrace TSC emulation
 * (reference: src/lib/tsc/tsc_test.c). Under the simulator the
 * counter is a pure function of simulated time (nominal 1 GHz), so
 * the printed deltas are exact. */
#include <stdint.h>
#include <stdio.h>
#include <unistd.h>

static inline uint64_t rdtsc(void) {
  uint32_t lo, hi;
  __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtscp(void) {
  uint32_t lo, hi, aux;
  __asm__ __volatile__("rdtscp" : "=a"(lo), "=d"(hi), "=c"(aux));
  return ((uint64_t)hi << 32) | lo;
}

int main(void) {
  uint64_t t0 = rdtsc();
  usleep(50000); /* 50 ms simulated */
  uint64_t t1 = rdtsc();
  uint64_t t2 = rdtscp();
  printf("t0 %llu\n", (unsigned long long)t0);
  printf("dt %llu\n", (unsigned long long)(t1 - t0));
  printf("p_ge %d\n", t2 >= t1);
  fflush(stdout);
  return 0;
}
