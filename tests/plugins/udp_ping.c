/* UDP ping client: sends `count` datagrams to server, awaits echoes,
 * prints round-trip times in *simulated* milliseconds. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: udp_ping <server_ip> <port> <count>\n");
    return 2;
  }
  const char *ip = argv[1];
  int port = atoi(argv[2]);
  int count = atoi(argv[3]);

  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons(port);
  dst.sin_addr.s_addr = inet_addr(ip);

  char buf[512];
  for (int i = 0; i < count; i++) {
    int n = snprintf(buf, sizeof buf, "ping %d", i);
    long t0 = now_ms();
    if (sendto(s, buf, (size_t)n, 0, (struct sockaddr *)&dst,
               sizeof dst) != n) {
      perror("sendto");
      return 1;
    }
    char rbuf[512];
    ssize_t r = recvfrom(s, rbuf, sizeof rbuf - 1, 0, NULL, NULL);
    if (r < 0) {
      perror("recvfrom");
      return 1;
    }
    rbuf[r] = 0;
    printf("reply %d: '%s' rtt_ms=%ld\n", i, rbuf, now_ms() - t0);
  }
  close(s);
  printf("done\n");
  fflush(stdout);
  return 0;
}
