/* Raw-syscall determinism probe: issues the startup-window syscalls
 * via syscall(2) directly — no libc wrappers, no vDSO — the way
 * static/musl/Go runtimes do. Outside strict-traps mode these bypass
 * virtualization (documented); under SHADOWTPU_STRICT_TRAPS=1 (or the
 * ptrace backend) they MUST trap and report simulated values. */
#define _GNU_SOURCE
#include <stdio.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  struct timespec ts;
  long r = syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &ts);
  printf("raw_clock %ld %ld.%09ld\n", r, (long)ts.tv_sec, ts.tv_nsec);

  long t = syscall(SYS_time, 0);
  printf("raw_time %ld\n", t);

  long pid = syscall(SYS_getpid);
  printf("raw_pid %ld\n", pid);

  unsigned char buf[8] = {0};
  long n = syscall(SYS_getrandom, buf, sizeof buf, 0);
  printf("raw_rand %ld ", n);
  for (int i = 0; i < 8; i++) printf("%02x", buf[i]);
  printf("\n");
  printf("done\n");
  return 0;
}
