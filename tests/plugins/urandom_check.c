/* /dev/urandom determinism: the simulator serves the RNG devices from
 * the host's seeded stream (native reads would be real randomness and
 * break run-to-run determinism). Prints hex of reads via open/read,
 * pread, and fstat's file type. */
#include <fcntl.h>
#include <stdio.h>
#include <sys/stat.h>
#include <unistd.h>

static void hex(const char *tag, unsigned char *b, int n) {
  printf("%s ", tag);
  for (int i = 0; i < n; i++) printf("%02x", b[i]);
  printf("\n");
}

int main(void) {
  int fd = open("/dev/urandom", O_RDONLY);
  if (fd < 0) { perror("open"); return 1; }
  unsigned char a[16], b[8];
  if (read(fd, a, sizeof a) != sizeof a) return 1;
  hex("r1", a, sizeof a);
  if (pread(fd, b, sizeof b, 0) != sizeof b) return 1;
  hex("r2", b, sizeof b);
  struct stat st;
  if (fstat(fd, &st) != 0) return 1;
  printf("chardev %d\n", S_ISCHR(st.st_mode) ? 1 : 0);
  close(fd);
  int fd2 = open("/dev/random", O_RDONLY);
  if (fd2 < 0) { perror("open2"); return 1; }
  if (read(fd2, b, sizeof b) != sizeof b) return 1;
  hex("r3", b, sizeof b);
  close(fd2);
  printf("done\n");
  return 0;
}
