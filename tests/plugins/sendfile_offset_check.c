/* NULL-offset sendfile(2) must advance the file description's offset
 * (kernel semantics) — a subsequent read(2) on the SAME fd continues
 * where sendfile stopped. Non-NULL offset must leave it untouched. */
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: sendfile_offset_check <ip> <port>\n");
    return 2;
  }
  /* pattern file: byte i = i & 0xff */
  int f = open("sfoff.bin", O_CREAT | O_TRUNC | O_RDWR, 0644);
  char buf[8192];
  for (int i = 0; i < (int)sizeof buf; i++)
    buf[i] = (char)(i & 0xff);
  if (write(f, buf, sizeof buf) != (long)sizeof buf) {
    perror("write");
    return 1;
  }
  lseek(f, 0, SEEK_SET);

  int s = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in d;
  memset(&d, 0, sizeof d);
  d.sin_family = AF_INET;
  d.sin_port = htons(atoi(argv[2]));
  d.sin_addr.s_addr = inet_addr(argv[1]);
  if (connect(s, (struct sockaddr *)&d, sizeof d) != 0) {
    perror("connect");
    return 1;
  }

  /* NULL offset: stream 4096 from position 0, fd offset must advance */
  long n = sendfile(s, f, NULL, 4096);
  printf("sf1 n=%ld\n", n);
  long pos = lseek(f, 0, SEEK_CUR);
  printf("pos after null-offset sendfile: %ld\n", pos);
  char probe[4];
  long r = read(f, probe, sizeof probe);
  printf("read n=%ld bytes %d %d %d %d\n", r, probe[0] & 0xff,
         probe[1] & 0xff, probe[2] & 0xff, probe[3] & 0xff);

  /* explicit offset: fd position must NOT move further */
  off_t off = 0;
  long before = lseek(f, 0, SEEK_CUR);
  n = sendfile(s, f, &off, 1024);
  printf("sf2 n=%ld off=%ld moved=%ld\n", n, (long)off,
         lseek(f, 0, SEEK_CUR) - before);
  close(s);
  return 0;
}
