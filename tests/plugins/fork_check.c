/* fork + wait4 under the simulator: the child is a real forked
 * process with its own virtual pid, simulated clocks stay coherent
 * across the tree, and the parent's blocking wait returns the child's
 * exit status at the simulated instant the child died. */
#include <stdio.h>
#include <stdlib.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static long now_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(void) {
  long t0 = now_ms();
  pid_t me = getpid();
  pid_t child = fork();
  if (child < 0) {
    perror("fork");
    return 1;
  }
  if (child == 0) {
    /* child: distinct pid, correct ppid, sleeps 200 ms sim time */
    printf("child pid!=parent %d ppid_ok %d\n", getpid() != me,
           getppid() == me);
    fflush(stdout);
    usleep(200 * 1000);
    _exit(42);
  }
  printf("parent sees child %d\n", child > 0 && child != me);
  int status = 0;
  pid_t r = waitpid(child, &status, 0);
  long waited = now_ms() - t0;
  printf("wait ret_ok %d exited %d code %d t_ms %ld\n", r == child,
         WIFEXITED(status), WEXITSTATUS(status), waited);

  /* second child, reaped with wait4(-1) */
  pid_t c2 = fork();
  if (c2 == 0)
    _exit(7);
  int st2 = 0;
  pid_t r2 = wait(&st2);
  printf("second ok %d code %d\n", r2 == c2, WEXITSTATUS(st2));

  /* no children left: ECHILD */
  printf("echild %d\n", wait(NULL) == -1);
  return 0;
}
