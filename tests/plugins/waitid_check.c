/* waitid over virtual children: WNOHANG empty, blocking WEXITED
 * reap with CLD_EXITED siginfo, WNOWAIT keeping the zombie. */
#define _GNU_SOURCE
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
  pid_t child = fork();
  if (child == 0) {
    usleep(100 * 1000);
    _exit(42);
  }
  siginfo_t si;
  memset(&si, 0, sizeof si);
  si.si_pid = -1;
  int r = waitid(P_PID, (id_t)child, &si, WEXITED | WNOHANG);
  printf("nohang r=%d pid=%d\n", r, (int)si.si_pid);
  memset(&si, 0, sizeof si);
  r = waitid(P_PID, (id_t)child, &si, WEXITED | WNOWAIT);
  printf("nowait r=%d pid_match=%d code_exited=%d status=%d\n", r,
         si.si_pid == child, si.si_code == CLD_EXITED, si.si_status);
  memset(&si, 0, sizeof si);
  r = waitid(P_ALL, 0, &si, WEXITED);
  printf("reap r=%d pid_match=%d status=%d\n", r, si.si_pid == child,
         si.si_status);
  r = waitid(P_ALL, 0, &si, WEXITED | WNOHANG);
  printf("after r=%d echild=%d\n", r, r == -1);
  printf("done\n");
  return 0;
}
