/* recvmmsg(2) semantics check (receiver side). Peer: udp_burst.
 *
 * Three scenarios against the simulated clock (deterministic, so the
 * printed sim-time deltas are exact):
 *  a) MSG_WAITFORONE on an empty blocking socket: waits for the first
 *     datagram, then drains without blocking again (2 arrive together
 *     -> n=2).
 *  b) 100 ms timeout, socket empty until one datagram arrives AFTER
 *     the timeout would have expired: the kernel only consults the
 *     timeout after each received datagram, so the call still returns
 *     that first datagram (n=1) at its arrival time.
 *  c) 600 ms timeout with one datagram mid-window: returns n=1 at the
 *     DEADLINE (timeout expiry ends the wait for more). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec / 1e9;
}

static int do_recvmmsg(int s, int vlen, int flags,
                       struct timespec *timeout) {
  static char bufs[8][256];
  struct mmsghdr msgs[8];
  struct iovec iovs[8];
  memset(msgs, 0, sizeof msgs);
  for (int i = 0; i < vlen; i++) {
    iovs[i].iov_base = bufs[i];
    iovs[i].iov_len = sizeof bufs[i];
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
  }
  return recvmmsg(s, msgs, vlen, flags, timeout);
}

int main(int argc, char **argv) {
  int port = argc > 1 ? atoi(argv[1]) : 9000;
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  a.sin_addr.s_addr = INADDR_ANY;
  if (bind(s, (struct sockaddr *)&a, sizeof a) != 0) {
    perror("bind");
    return 1;
  }
  /* a) WAITFORONE with d1+d2 already queued (sleep past their
   * arrival): returns both without blocking */
  usleep(700 * 1000);
  double ta0 = now_s();
  int n = do_recvmmsg(s, 8, MSG_WAITFORONE, NULL);
  printf("a n=%d dt=%.3f\n", n, now_s() - ta0);

  /* b) empty socket, 100 ms timeout, next datagram later than that */
  struct timespec tb = {0, 100 * 1000 * 1000};
  double tb0 = now_s();
  n = do_recvmmsg(s, 4, 0, &tb);
  printf("b n=%d dt=%.3f\n", n, now_s() - tb0);

  /* c) 600 ms window, one datagram mid-window: returns at deadline */
  struct timespec tc = {0, 600 * 1000 * 1000};
  double tc0 = now_s();
  n = do_recvmmsg(s, 4, 0, &tc);
  printf("c n=%d dt=%.3f\n", n, now_s() - tc0);
  return 0;
}
