/* POSIX record locks across two processes on one simulated host
 * (virtual lock table; F_GETLK reports the holder's VIRTUAL pid) +
 * deterministic fstatfs. mode=hold: write-lock [0,100) and sleep;
 * mode=probe (started later): conflicting F_SETLK fails EAGAIN,
 * F_GETLK names the holder, a disjoint range and a same-process
 * re-lock succeed, and after the holder exits the range is free. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/vfs.h>
#include <unistd.h>

static int setlk(int fd, short type, long start, long len) {
  struct flock fl = {0};
  fl.l_type = type;
  fl.l_whence = SEEK_SET;
  fl.l_start = start;
  fl.l_len = len;
  return fcntl(fd, F_SETLK, &fl);
}

int main(int argc, char **argv) {
  const char *mode = argc > 1 ? argv[1] : "hold";
  int fd = open("lk.bin", O_CREAT | O_RDWR, 0644);
  if (fd < 0) { perror("open"); return 1; }

  if (!strcmp(mode, "hold")) {
    if (setlk(fd, F_WRLCK, 0, 100) != 0) { perror("lock"); return 1; }
    printf("held pid=%d\n", (int)getpid());
    fflush(stdout);
    usleep(500000);
    printf("done\n");
    return 0;
  }

  /* probe (starts while hold sleeps) */
  printf("conflict %d\n",
         setlk(fd, F_WRLCK, 50, 10) == -1 && errno == EAGAIN);
  struct flock q = {0};
  q.l_type = F_WRLCK;
  q.l_whence = SEEK_SET;
  q.l_start = 50;
  q.l_len = 10;
  if (fcntl(fd, F_GETLK, &q) != 0) { perror("getlk"); return 1; }
  printf("getlk type=%d pid=%d\n", (int)q.l_type, (int)q.l_pid);
  printf("disjoint %d\n", setlk(fd, F_WRLCK, 200, 10) == 0);
  int fd2 = open("lk.bin", O_RDWR);
  printf("same_process %d\n", setlk(fd2, F_WRLCK, 205, 10) == 0);

  /* OFD locks are owned by the open file DESCRIPTION: the same
   * process's second description conflicts, and GETLK reports -1 */
  struct flock ofl = {0};
  ofl.l_type = F_WRLCK;
  ofl.l_whence = SEEK_SET;
  ofl.l_start = 400;
  ofl.l_len = 10;
  printf("ofd_first %d\n", fcntl(fd, F_OFD_SETLK, &ofl) == 0);
  struct flock ofl2 = ofl;
  printf("ofd_conflict %d\n",
         fcntl(fd2, F_OFD_SETLK, &ofl2) == -1 && errno == EAGAIN);
  ofl2 = ofl;
  if (fcntl(fd2, F_OFD_GETLK, &ofl2) != 0) { perror("ofdgetlk"); return 1; }
  printf("ofd_getlk type=%d pid=%d\n", (int)ofl2.l_type,
         (int)ofl2.l_pid);

  struct statfs sf;
  if (fstatfs(fd, &sf) != 0) { perror("fstatfs"); return 1; }
  printf("fstatfs type=%lx bsize=%ld namelen=%ld\n",
         (unsigned long)sf.f_type, (long)sf.f_bsize,
         (long)sf.f_namelen);

  usleep(600000);               /* the holder has exited by now */
  printf("freed %d\n", setlk(fd, F_WRLCK, 50, 10) == 0);
  printf("done\n");
  return 0;
}
