/* Per-host file isolation: the same RELATIVE path on two hosts must
 * land in each host's own data directory (plugin cwd == host dir).
 * Writes argv[1] into state.txt, reads it back, prints it; also
 * prints the first line of /etc/hosts (the SIMULATED name map). */
#include <stdio.h>
#include <string.h>
#include <sys/stat.h>

int main(int argc, char **argv) {
  const char *tag = argc > 1 ? argv[1] : "none";
  FILE *f = fopen("state.txt", "w");
  if (!f) { perror("fopen w"); return 1; }
  fprintf(f, "%s", tag);
  fclose(f);
  char buf[256] = {0};
  f = fopen("state.txt", "r");
  if (!f) { perror("fopen r"); return 1; }
  fgets(buf, sizeof buf, f);
  fclose(f);
  printf("state %s\n", buf);
  f = fopen("/etc/hosts", "r");
  if (!f) { perror("hosts"); return 1; }
  int hosts_lines = 0;
  long hosts_bytes = 0;
  while (fgets(buf, sizeof buf, f)) {
    hosts_lines++;
    hosts_bytes += (long)strlen(buf);
  }
  fclose(f);
  printf("hosts_lines %d\n", hosts_lines);
  /* path-stat must agree with the SERVED content, not the real file */
  struct stat st;
  if (stat("/etc/hosts", &st) != 0) { perror("stat"); return 1; }
  printf("stat_coherent %d\n", (long)st.st_size == hosts_bytes);
  f = fopen("/etc/hosts", "a");
  printf("hosts_readonly %d\n", f == NULL);
  if (f) fclose(f);
  printf("done\n");
  return 0;
}
