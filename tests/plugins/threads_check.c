/* Managed-thread check (reference: src/main/host/syscall/clone.c +
 * src/test/threads, src/test/clone): pthread_create/join over the
 * simulator's clone handshake, virtual tids, futex-backed join, a
 * contended mutex, and per-thread simulated sleeps.
 *
 * Expected (deterministic): child vtids are main+1..main+3 in creation
 * order; each thread sleeps (i+1)*10 ms of SIMULATED time; main's
 * monotonic elapsed across all joins is exactly 30 ms; counter == 3.
 */
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

static pthread_mutex_t lock = PTHREAD_MUTEX_INITIALIZER;
static int counter = 0;
static long main_tid;

static int64_t now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000 + ts.tv_nsec;
}

static void *worker(void *argv) {
  long i = (long)argv;
  long tid = syscall(SYS_gettid);
  struct timespec ts = {0, (long)(i + 1) * 10 * 1000000};
  nanosleep(&ts, NULL);
  pthread_mutex_lock(&lock);
  counter++;
  printf("thread %ld dtid=%ld slept=%ldms counter=%d\n", i,
         tid - main_tid, (i + 1) * 10, counter);
  pthread_mutex_unlock(&lock);
  return (void *)(tid - main_tid);
}

int main(void) {
  main_tid = syscall(SYS_gettid);
  printf("main tid==pid: %d\n", main_tid == getpid());
  int64_t t0 = now_ns();

  pthread_t th[3];
  for (long i = 0; i < 3; i++) {
    if (pthread_create(&th[i], NULL, worker, (void *)i) != 0) {
      printf("pthread_create %ld failed\n", i);
      return 1;
    }
  }
  for (long i = 0; i < 3; i++) {
    void *ret;
    pthread_join(th[i], &ret);
    printf("joined %ld ret=%ld\n", i, (long)ret);
  }
  int64_t dt_ms = (now_ns() - t0) / 1000000;
  printf("all joined: counter=%d elapsed_ms=%lld\n", counter,
         (long long)dt_ms);
  fflush(stdout);
  return 0;
}
