#include <stdio.h>
#include <stdlib.h>
#include <sys/wait.h>
int main(void) {
  int rc = system("echo spawned-ok");
  printf("system rc=%d exited=%d status=%d\n", rc,
         WIFEXITED(rc), WEXITSTATUS(rc));
  fflush(stdout);
  return 0;
}
