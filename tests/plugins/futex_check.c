/* Futex emulation check (reference: src/main/host/syscall/futex.c):
 * 1. FUTEX_WAIT with a mismatched expected value -> EAGAIN instantly.
 * 2. FUTEX_WAKE with no waiters -> 0.
 * 3. FUTEX_WAIT with a 50 ms timeout -> ETIMEDOUT, and the *simulated*
 *    clock must have advanced by exactly that timeout.
 */
#include <errno.h>
#include <linux/futex.h>
#include <stdint.h>
#include <stdio.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

static uint32_t word = 42;

static long fut(int op, uint32_t val, const struct timespec *to) {
    return syscall(SYS_futex, &word, op, val, to, NULL, 0);
}

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000 + ts.tv_nsec;
}

int main(void) {
    long r = fut(FUTEX_WAIT, 41, NULL);      /* value mismatch */
    printf("mismatch: r=%ld errno=%d\n", r, r < 0 ? errno : 0);

    r = fut(FUTEX_WAKE, 128, NULL);          /* nobody waiting */
    printf("wake: r=%ld\n", r);

    int64_t t0 = now_ns();
    struct timespec to = {0, 50 * 1000000};  /* 50 ms */
    r = fut(FUTEX_WAIT, 42, &to);
    int64_t dt = now_ns() - t0;
    printf("wait: r=%ld errno=%d dt_ms=%lld\n", r, r < 0 ? errno : 0,
           (long long)(dt / 1000000));
    fflush(stdout);
    return 0;
}
