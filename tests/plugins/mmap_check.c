/* mmap of a DATA-DIR file (an emulated fd): under ptrace the mapping
 * is realized through the simulator's /proc fd; under preload mmap
 * fails with ENODEV and the app falls back to read() — both paths
 * must see identical bytes. Also exercises MAP_SHARED write-through:
 * bytes stored via the mapping must be visible to pread on the same
 * (emulated) fd. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

int main(void) {
  const char payload[] = "0123456789abcdef0123456789abcdef";
  int fd = open("mapme.bin", O_CREAT | O_RDWR, 0644);
  if (fd < 0) { perror("open"); return 1; }
  if (write(fd, payload, 32) != 32) { perror("write"); return 1; }

  void *m = mmap(NULL, 32, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (m == MAP_FAILED) {
    printf("mmap_errno %d\n", errno);
    char buf[33] = {0};
    if (pread(fd, buf, 32, 0) != 32) { perror("pread"); return 1; }
    printf("fallback_read %d\n", memcmp(buf, payload, 32) == 0);
    printf("done\n");
    return 0;
  }
  printf("mmap_errno 0\n");
  printf("map_read %d\n", memcmp(m, payload, 32) == 0);
  memcpy((char *)m + 8, "WRITTEN!", 8);
  if (msync(m, 32, MS_SYNC) != 0) { perror("msync"); return 1; }
  char buf[33] = {0};
  if (pread(fd, buf, 32, 0) != 32) { perror("pread2"); return 1; }
  printf("write_through %d\n", memcmp(buf + 8, "WRITTEN!", 8) == 0);
  if (munmap(m, 32) != 0) { perror("munmap"); return 1; }
  close(fd);
  printf("done\n");
  return 0;
}
