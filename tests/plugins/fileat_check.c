/* The fd-mediated file family (ref file.c/fileat.c parity): dirfd-
 * relative openat/mkdirat/renameat/unlinkat/linkat/symlinkat/
 * readlinkat/faccessat, fd ops (ftruncate/fsync/fallocate/fchmod/
 * flock/pread/pwrite), sorted deterministic getdents, and data-dir
 * confinement of ".." escapes. Prints one "label value" line per
 * check; the harness asserts exact output. */
#define _GNU_SOURCE
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <linux/falloc.h>
#include <stdio.h>
#include <string.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <sys/sysmacros.h>
#include <sys/uio.h>
#include <unistd.h>

static void check(const char *label, int ok) {
  printf("%s %d\n", label, ok);
}

int main(void) {
  /* -- a subdir opened as a dirfd anchors the whole at-family -- */
  check("mkdir", mkdir("sub", 0755) == 0);
  int dirfd = open("sub", O_RDONLY | O_DIRECTORY);
  check("opendirfd", dirfd >= 0);

  /* -- create/write/pread through the dirfd -- */
  int fd = openat(dirfd, "a.txt", O_CREAT | O_RDWR, 0644);
  check("openat", fd >= 0);
  check("write", write(fd, "hello world", 11) == 11);
  char buf[64] = {0};
  check("pread", pread(fd, buf, 5, 6) == 5 && !strcmp(buf, "world"));
  memset(buf, 0, sizeof buf);
  struct iovec iov[2] = {{buf, 3}, {buf + 8, 2}};
  check("preadv", preadv(fd, iov, 2, 6) == 5 &&
        !strncmp(buf, "wor", 3) && !strncmp(buf + 8, "ld", 2));
  /* the kernel validates the offset before the zero-seg shortcut */
  errno = 0;
  check("preadv_badoff", preadv(fd, iov, 0, -1) == -1 &&
        errno == EINVAL);
  check("pwrite", pwrite(fd, "WORLD", 5, 6) == 5);
  check("lseek", lseek(fd, 0, SEEK_SET) == 0);
  memset(buf, 0, sizeof buf);
  check("read", read(fd, buf, 11) == 11 &&
        !strcmp(buf, "hello WORLD"));
  struct stat st;
  check("fstat_size", fstat(fd, &st) == 0 && st.st_size == 11);
  check("ftruncate", ftruncate(fd, 5) == 0 && fstat(fd, &st) == 0 &&
        st.st_size == 5);
  check("fsync", fsync(fd) == 0);
  check("fdatasync", fdatasync(fd) == 0);
  check("fchmod", fchmod(fd, 0600) == 0 && fstat(fd, &st) == 0 &&
        (st.st_mode & 07777) == 0600);

  /* -- stat through the dirfd (fstatat) -- */
  check("fstatat", fstatat(dirfd, "a.txt", &st, 0) == 0 &&
        st.st_size == 5);

  /* -- links -- */
  check("symlinkat", symlinkat("a.txt", dirfd, "ln") == 0);
  ssize_t n = readlinkat(dirfd, "ln", buf, sizeof buf);
  check("readlinkat", n == 5 && !strncmp(buf, "a.txt", 5));
  check("fstatat_nofollow",
        fstatat(dirfd, "ln", &st, AT_SYMLINK_NOFOLLOW) == 0 &&
        S_ISLNK(st.st_mode));
  check("linkat", linkat(dirfd, "a.txt", dirfd, "hard", 0) == 0);
  check("nlink2", fstatat(dirfd, "hard", &st, 0) == 0 &&
        st.st_nlink == 2);
  check("renameat", renameat(dirfd, "hard", dirfd, "hard2") == 0 &&
        faccessat(dirfd, "hard2", F_OK, 0) == 0 &&
        faccessat(dirfd, "hard", F_OK, 0) != 0);
  check("faccessat_rw", faccessat(dirfd, "a.txt", R_OK | W_OK, 0) == 0);

  /* -- sorted deterministic getdents -- */
  DIR *d = fdopendir(openat(dirfd, ".", O_RDONLY | O_DIRECTORY));
  check("fdopendir", d != NULL);
  char order[256] = {0};
  if (d) {
    struct dirent *e;
    while ((e = readdir(d)) != NULL) {
      strncat(order, e->d_name, sizeof order - strlen(order) - 2);
      strncat(order, ",", sizeof order - strlen(order) - 2);
    }
    closedir(d);
  }
  printf("dirents %s\n", order);

  /* -- subdirectories via mkdirat / unlinkat(AT_REMOVEDIR) -- */
  check("mkdirat", mkdirat(dirfd, "d2", 0755) == 0);
  check("rmdirat", unlinkat(dirfd, "d2", AT_REMOVEDIR) == 0);

  /* -- flock: EX held on one description conflicts with another -- */
  int fd2 = openat(dirfd, "a.txt", O_RDWR);
  check("flock_ex", flock(fd, LOCK_EX) == 0);
  check("flock_conflict",
        flock(fd2, LOCK_EX | LOCK_NB) == -1 && errno == EWOULDBLOCK);
  check("flock_un", flock(fd, LOCK_UN) == 0);
  check("flock_regrab", flock(fd2, LOCK_EX | LOCK_NB) == 0);
  close(fd2);

  /* -- confinement: ".." escapes out of the data dir are refused -- */
  int esc = open("../../escape.txt", O_CREAT | O_WRONLY, 0644);
  check("escape_rel", esc < 0 && errno == EACCES);
  esc = openat(dirfd, "../../../escape.txt", O_CREAT | O_WRONLY, 0644);
  check("escape_dirfd", esc < 0 && errno == EACCES);
  check("unlinkat_ln", unlinkat(dirfd, "ln", 0) == 0);
  check("unlinkat_hard2", unlinkat(dirfd, "hard2", 0) == 0);
  close(fd);
  close(dirfd);

  /* -- chdir coherence: relative resolution must follow the cwd -- */
  check("chdir", chdir("sub") == 0);
  FILE *cf = fopen("cwdfile.txt", "w");
  check("cwd_fopen", cf != NULL);
  if (cf) { fputs("incwd", cf); fclose(cf); }
  check("cwd_stat", stat("cwdfile.txt", &st) == 0);
  check("chdir_up", chdir("..") == 0);
  check("cwd_back", stat("sub/cwdfile.txt", &st) == 0);

  /* -- dirent/stat identity: d_ino of a listed file equals st_ino -- */
  d = opendir("sub");
  long d_ino = -1;
  if (d) {
    struct dirent *e;
    while ((e = readdir(d)) != NULL)
      if (!strcmp(e->d_name, "a.txt")) d_ino = (long)e->d_ino;
    closedir(d);
  }
  check("dino_matches_stat",
        stat("sub/a.txt", &st) == 0 && d_ino == (long)st.st_ino);

  /* -- renameat2 RENAME_EXCHANGE: true atomic swap -- */
  FILE *xa = fopen("xa.txt", "w");
  FILE *xb = fopen("xb.txt", "w");
  check("exch_setup", xa && xb);
  if (xa) { fputs("AAA", xa); fclose(xa); }
  if (xb) { fputs("B", xb); fclose(xb); }
  check("exch", renameat2(AT_FDCWD, "xa.txt", AT_FDCWD, "xb.txt",
                          RENAME_EXCHANGE) == 0);
  check("exch_sizes",
        stat("xa.txt", &st) == 0 && st.st_size == 1 &&
        stat("xb.txt", &st) == 0 && st.st_size == 3);
  check("exch_missing",
        renameat2(AT_FDCWD, "xa.txt", AT_FDCWD, "nosuch.txt",
                  RENAME_EXCHANGE) == -1 && errno == ENOENT);

  /* -- mknod(at): FIFOs and regular files land confined; device
   * nodes answer EPERM like the kernel does unprivileged -- */
  check("mknod_fifo", mknod("f.fifo", S_IFIFO | 0644, 0) == 0);
  check("fifo_stat",
        stat("f.fifo", &st) == 0 && S_ISFIFO(st.st_mode));
  check("mknod_reg", mknod("plain.txt", S_IFREG | 0644, 0) == 0);
  check("mknod_sock", mknod("s.sock", S_IFSOCK | 0600, 0) == 0);
  check("sock_stat",
        stat("s.sock", &st) == 0 && S_ISSOCK(st.st_mode));
  check("mknod_dev",
        mknod("dev0", S_IFCHR | 0644, makedev(1, 3)) == -1 &&
        errno == EPERM);
  check("mknod_sock_exists",
        mknod("s.sock", S_IFSOCK | 0600, 0) == -1 && errno == EEXIST);
  check("mknod_dir_einval",
        mknod("dx", S_IFDIR | 0755, 0) == -1 && errno == EINVAL);

  /* -- advisory I/O: deterministic successes after validation -- */
  int af = open("plain.txt", O_RDWR);
  check("adv_open", af >= 0);
  check("adv_write", write(af, "x", 1) == 1);
  check("fadvise",
        posix_fadvise(af, 0, 0, POSIX_FADV_SEQUENTIAL) == 0);
  check("fadvise_bad", posix_fadvise(af, 0, 0, 99) == EINVAL);
  check("readahead", readahead(af, 0, 4096) == 0);
  check("falloc", posix_fallocate(af, 0, 8192) == 0);
  /* punch a hole: size stays (KEEP_SIZE) but the range zeroes */
  check("punch", fallocate(af, FALLOC_FL_PUNCH_HOLE |
                           FALLOC_FL_KEEP_SIZE, 0, 4096) == 0);
  struct stat pst;
  check("punch_size", fstat(af, &pst) == 0 && pst.st_size == 8192);
  check("sync_range",
        sync_file_range(af, 0, 0, SYNC_FILE_RANGE_WRITE) == 0);
  check("syncfs", syncfs(af) == 0);
  close(af);
  printf("done\n");
  return 0;
}
