"""Hybrid execution: CPU host emulation + batched device network model.

The thesis boundary of the framework (SURVEY §7 stage 6, reference
worker.c:520-579): syscall interposition and the in-simulator
TCP/UDP/NIC stacks stay on the CPU, while each round's egress packets
are judged (latency gather + counter-RNG drop roll) on the device in
one batch. These tests pin the correctness contract: a hybrid run's
event trace is bit-identical to the pure-CPU oracle's.
"""

import os
import subprocess

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller

PLUGIN_DIR = os.path.join(os.path.dirname(__file__), "plugins")

GML_LOSSLESS = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.0 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
]"""

GML_LOSSY = GML_LOSSLESS.replace("packet_loss 0.0", "packet_loss 0.02")


def _indent(text: str, n: int) -> str:
    return "\n".join(" " * n + line for line in text.splitlines())


@pytest.fixture(scope="module")
def tcp_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("plugins")
    bins = {}
    for name in ("tcp_client", "tcp_server"):
        exe = out / name
        subprocess.run(
            ["cc", "-O1", "-pthread", "-o", str(exe),
             os.path.join(PLUGIN_DIR, f"{name}.c")],
            check=True, capture_output=True)
        bins[name] = str(exe)
    return bins


def phold_cfg(policy: str, gml: str) -> str:
    return f"""
general:
  stop_time: 2s
  seed: 7
network:
  graph:
    type: gml
    inline: |
{_indent(gml, 6)}
experimental:
  scheduler_policy: {policy}
hosts:
  left:
    quantity: 8
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload=3 size=64
      start_time: 10ms
  right:
    quantity: 8
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload=3 size=64
      start_time: 10ms
"""


def run_cfg(yaml_text: str, trace: bool = True):
    trace_list = [] if trace else None
    c = Controller(load_config_str(yaml_text), trace=trace_list)
    stats = c.run()
    hosts = c.sim.hosts
    return stats, trace_list, hosts


def test_hybrid_phold_trace_equals_cpu():
    """Model apps through the deferred device judgment produce the
    exact event schedule of the synchronous CPU path."""
    s_cpu, t_cpu, h_cpu = run_cfg(phold_cfg("serial", GML_LOSSY))
    s_hyb, t_hyb, h_hyb = run_cfg(phold_cfg("hybrid", GML_LOSSY))
    assert s_cpu.events_executed == s_hyb.events_executed
    assert s_cpu.packets_sent == s_hyb.packets_sent
    assert s_cpu.packets_dropped == s_hyb.packets_dropped
    assert t_cpu == t_hyb
    for a, b in zip(h_cpu, h_hyb):
        assert a.trace_checksum == b.trace_checksum, a.name


def test_hybrid_selfloop_runahead_trace_equals_cpu():
    """A runahead window wider than the self-path latency makes
    self-destined deliveries land BELOW the barrier; they are exempt
    from the causality bump, so hybrid must judge them synchronously to
    keep per-host time order identical to the serial oracle."""
    extra = "  runahead: 100ms\n"
    cfg_s = phold_cfg("serial", GML_LOSSY).replace(
        "  scheduler_policy: serial", "  scheduler_policy: serial\n"
        + extra).replace("msgload=3 size=64",
                         "msgload=3 size=64 selfloop=1")
    cfg_h = phold_cfg("hybrid", GML_LOSSY).replace(
        "  scheduler_policy: hybrid", "  scheduler_policy: hybrid\n"
        + extra).replace("msgload=3 size=64",
                         "msgload=3 size=64 selfloop=1")
    s_cpu, t_cpu, h_cpu = run_cfg(cfg_s)
    s_hyb, t_hyb, h_hyb = run_cfg(cfg_h)
    assert s_cpu.packets_sent == s_hyb.packets_sent > 0
    assert t_cpu == t_hyb
    for a, b in zip(h_cpu, h_hyb):
        assert a.trace_checksum == b.trace_checksum, a.name


def test_tpu_policy_falls_back_to_hybrid_for_unvectorized_apps():
    """scheduler_policy: tpu on a config with no device twin runs
    hybrid instead of failing (tgen_tcp uses the full socket stack)."""
    yaml_text = f"""
general:
  stop_time: 4s
  seed: 3
network:
  graph:
    type: gml
    inline: |
{_indent(GML_LOSSLESS, 6)}
experimental:
  scheduler_policy: %s
hosts:
  server:
    network_node_id: 0
    processes:
    - path: model:tgen_tcp_server
      args: port=80
      start_time: 100ms
  client:
    network_node_id: 1
    processes:
    - path: model:tgen_tcp_client
      args: server=server port=80 size=50000
      start_time: 200ms
"""
    s_cpu, t_cpu, h_cpu = run_cfg(yaml_text % "serial")
    s_hyb, t_hyb, h_hyb = run_cfg(yaml_text % "tpu")
    assert s_hyb.packets_delivered > 0
    assert t_cpu == t_hyb
    for a, b in zip(h_cpu, h_hyb):
        assert a.trace_checksum == b.trace_checksum, a.name


def managed_tcp_cfg(policy: str, data_dir: str, bins: dict,
                    loss: bool = False) -> str:
    gml = GML_LOSSY if loss else GML_LOSSLESS
    return f"""
general:
  stop_time: 60s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
{_indent(gml, 6)}
experimental:
  scheduler_policy: {policy}
hosts:
  server:
    network_node_id: 0
    processes:
    - path: {bins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {bins['tcp_client']}
      args: 11.0.0.1 8080 200000
      start_time: 2s
"""


def _stdout_of(data_dir: str, host: str, exe: str) -> str:
    d = os.path.join(data_dir, "hosts", host)
    for f in sorted(os.listdir(d)):
        if f.startswith(exe) and f.endswith(".stdout"):
            with open(os.path.join(d, f)) as fh:
                return fh.read()
    raise FileNotFoundError(f"no stdout for {exe} in {d}")


@pytest.mark.parametrize("loss", [False, True],
                         ids=["lossless", "lossy"])
def test_hybrid_managed_tcp_trace_equals_cpu(tcp_bins, tmp_path, loss):
    """The round-3 north star: REAL executables (tcp_client/tcp_server
    under seccomp interposition) running with scheduler_policy: tpu —
    which routes their packets through the device network model — with
    a trace checksum equal to the pure-CPU-policy run."""
    results = {}
    for policy in ("serial", "tpu"):
        data = str(tmp_path / policy / "shadow.data")
        cfg = load_config_str(
            managed_tcp_cfg(policy, data, tcp_bins, loss=loss))
        c = Controller(cfg)
        stats = c.run()
        assert stats.ok
        if policy == "tpu":
            # fell back to hybrid: manager path, judge live (small
            # rounds may stay on the CPU side of the adaptive split)
            assert c.manager is not None
            j = c.manager.net_judge
            assert j is not None
            assert j.packets + j.cpu_packets > 0
        results[policy] = (
            [(h.name, h.trace_checksum, h.packets_sent,
              h.packets_dropped) for h in c.sim.hosts],
            _stdout_of(data, "server", "tcp_server")
            + _stdout_of(data, "client", "tcp_client"),
        )
    assert results["serial"][0] == results["tpu"][0]
    assert results["serial"][1] == results["tpu"][1]
    # the transfer actually completed
    assert "sum" in results["tpu"][1]


def test_adaptive_judge_trace_invariant():
    """The adaptive CPU/device judge split (hybrid_judge_min_batch) is
    a pure wall-clock decision: forcing every round to the device
    (min_batch 0) and forcing every round to the CPU (min_batch 1e9)
    both produce the serial oracle's exact trace, and the counters
    prove each path actually ran."""
    base = phold_cfg("hybrid", GML_LOSSY)
    s_ser, t_ser, h_ser = run_cfg(phold_cfg("serial", GML_LOSSY))

    cfg_dev = base.replace(
        "  scheduler_policy: hybrid",
        "  scheduler_policy: hybrid\n  hybrid_judge_min_batch: 0")
    c = Controller(load_config_str(cfg_dev), trace=(t_dev := []))
    c.run()
    j = c.manager.net_judge
    assert j.batches > 0 and j.cpu_batches == 0
    assert t_dev == t_ser
    assert [h.trace_checksum for h in c.sim.hosts] == \
        [h.trace_checksum for h in h_ser]

    cfg_cpu = base.replace(
        "  scheduler_policy: hybrid",
        "  scheduler_policy: hybrid\n"
        "  hybrid_judge_min_batch: 1000000000")
    c = Controller(load_config_str(cfg_cpu), trace=(t_cpu := []))
    c.run()
    j = c.manager.net_judge
    assert j.cpu_batches > 0 and j.batches == 0
    assert t_cpu == t_ser
    assert [h.trace_checksum for h in c.sim.hosts] == \
        [h.trace_checksum for h in h_ser]
