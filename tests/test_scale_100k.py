"""100k-host topology/config pipeline support (the scale-out rung's
prerequisite): the example config must build into the sharded device
twin in seconds, not minutes — the per-group arg parse memo and the
lazy host RNG keep the build O(H) with small constants."""

import time

import pytest

from shadow_tpu.config import load_config
from shadow_tpu.core.controller import Controller


@pytest.mark.slow
def test_tgen_100000_builds_into_sharded_device_twin():
    cfg = load_config("examples/tgen_100000.yaml")
    assert cfg.total_hosts() == 100_000
    # build-only check: skip the capacity warm-up (it would compile
    # and run a real device slice; the multichip bench rung owns that)
    cfg.experimental.capacity_plan = "static"
    cfg.experimental.exchange = "all_to_all"
    t0 = time.perf_counter()
    c = Controller(cfg)
    build_s = time.perf_counter() - t0
    assert len(c.sim.hosts) == 100_000
    eng = c.runner.engine
    assert eng.H_pad % eng.n_shards == 0
    assert eng.H_pad >= 100_000
    # the [H, E] state builds on device from [H] vectors — init must
    # stay cheap even at this width
    state = eng.init_state(c.sim.starts)
    assert state["ht"].shape == (eng.H_pad,
                                 eng.config.event_capacity)
    # loose sanity bound: the 10k build is ~1s; 100k must not
    # regress to minutes (pre-memo it extrapolated to ~40s)
    assert build_s < 120, f"100k-host build took {build_s:.0f}s"


def test_parse_kv_args_memo_is_pure():
    from shadow_tpu.models.base import parse_kv_args

    a = parse_kv_args("server=srv size=1KiB count=2")
    b = parse_kv_args("server=srv size=1KiB count=2")
    assert a == b == {"server": "srv", "size": "1KiB", "count": "2"}
    a["server"] = "mutated"          # callers may mutate their dict
    assert parse_kv_args("server=srv size=1KiB count=2")["server"] \
        == "srv"


def test_seeded_random_lazy_rng_is_bit_identical():
    from shadow_tpu.utils.rng import SeededRandom

    a, b = SeededRandom(7), SeededRandom(7)
    assert a.child("x").seed == b.child("x").seed
    assert a.random() == b.random()
    assert a.randint(0, 100) == b.randint(0, 100)
