"""DNS, CPU model, tracker heartbeats, pcap capture."""

import logging
import struct

import pytest

from shadow_tpu import simtime
from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.host.cpu import Cpu
from shadow_tpu.routing.dns import Dns


def test_dns_assignment():
    dns = Dns()
    a = dns.register(0, "alice")
    b = dns.register(1, "bob")
    assert a.ip != b.ip
    assert a.ip_str.startswith("11.")
    assert dns.resolve_name("alice") is a
    assert dns.resolve_ip(a.ip_str) is a
    assert dns.address_of(1) is b
    with pytest.raises(ValueError):
        dns.register(2, "alice")


def test_dns_requested_ip_and_reserved_skip():
    dns = Dns()
    a = dns.register(0, "pinned", requested_ip="100.1.2.3")
    assert a.ip_str == "100.1.2.3"
    # reserved ranges are refused -> auto-assign
    b = dns.register(1, "lan", requested_ip="192.168.1.1")
    assert not b.ip_str.startswith("192.168.")


def test_dns_hosts_file(tmp_path):
    dns = Dns()
    dns.register(0, "alice")
    dns.register(1, "bob")
    p = tmp_path / "hosts"
    dns.write_hosts_file(str(p))
    text = p.read_text()
    assert "localhost" in text
    assert "alice" in text and "bob" in text


def test_cpu_model_blocks_and_recovers():
    cpu = Cpu(freq_khz=1_000_000, raw_freq_khz=2_000_000)
    # scaling: native 1ms at half speed -> 2ms virtual
    assert cpu.scale(1_000_000) == 2_000_000
    cpu.update_time(0)
    assert not cpu.is_blocked(0)
    cpu.add_delay(5 * simtime.SIMTIME_ONE_MILLISECOND)   # 10ms virtual
    assert cpu.is_blocked(0)
    d = cpu.delay_until_ready(0)
    assert d >= 10 * simtime.SIMTIME_ONE_MILLISECOND
    assert not cpu.is_blocked(20 * simtime.SIMTIME_ONE_MILLISECOND)


PHOLD_CPU_YAML = """
general:
  stop_time: 2s
  seed: 3
  heartbeat_interval: 500ms
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler_policy: serial}
hosts:
  peer:
    quantity: 4
    processes:
    - {path: "model:phold", args: "msgload=1", start_time: 100ms}
"""


def test_heartbeat_lines_emitted(caplog):
    with caplog.at_level(logging.INFO, logger="shadow_tpu.heartbeat"):
        Controller(load_config_str(PHOLD_CPU_YAML)).run()
    lines = [r.getMessage() for r in caplog.records
             if "shadow-heartbeat" in r.getMessage()]
    assert any("[node-header]" in ln for ln in lines)
    node_lines = [ln for ln in lines if "[node]" in ln]
    # 4 hosts x 3 heartbeats (0.5, 1.0, 1.5s)
    assert len(node_lines) == 12


PCAP_YAML = """
general: {stop_time: 10s, seed: 1}
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler_policy: serial}
hosts:
  server:
    pcap_directory: "%s"
    processes:
    - {path: "model:tgen_tcp_server", args: "size=10KiB", start_time: 1s}
  client:
    processes:
    - {path: "model:tgen_tcp_client",
       args: "server=server size=10KiB count=1", start_time: 2s}
"""


def test_pcap_capture(tmp_path):
    cfg = load_config_str(PCAP_YAML % tmp_path)
    c = Controller(cfg)
    c.run()
    pcap = tmp_path / "server-eth.pcap"
    assert pcap.exists()
    data = pcap.read_bytes()
    magic, = struct.unpack("<I", data[:4])
    assert magic == 0xA1B2C3D4
    assert len(data) > 24 + 16      # header + at least one record


def test_phold_cpuload_slows_simulation():
    base = load_config_str(PHOLD_CPU_YAML)
    loaded = load_config_str(
        PHOLD_CPU_YAML.replace("msgload=1", "msgload=1 cpuload=100"))
    s_base = Controller(base).run()
    s_load = Controller(loaded).run()
    # 100ms of virtual CPU per received message throttles the event
    # rate well below the unloaded run
    assert s_load.events_executed < s_base.events_executed / 2


def test_cpu_load_delays_events():
    from shadow_tpu.models import register_model
    from shadow_tpu.models.base import ModelApp

    class Burner(ModelApp):
        def boot(self, ctx):
            ctx.send((self.host_id + 1) % self.n_hosts, 64)

        def on_packet(self, ctx, src, size, data):
            ctx.consume_cpu(50 * simtime.SIMTIME_ONE_MILLISECOND)
            ctx.send((self.host_id + 1) % self.n_hosts, 64)

    register_model("burner", Burner)
    base = """
general: {stop_time: 2s, seed: 1}
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler_policy: serial}
hosts:
  peer:
    quantity: 2
    processes:
    - {path: "model:burner", start_time: 0ms}
"""
    c = Controller(load_config_str(base))
    stats = c.run()
    # each hop now costs ~latency + cpu backlog; with 50ms burn per
    # packet the ring can't exceed ~2s/50ms events per chain
    assert stats.events_executed < 100
