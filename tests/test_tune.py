"""Strategy autotuner (shadow_tpu/tune/, docs/autotune.md).

Fast tier-1 coverage of the plan space, the PLAN record lifecycle
(save / load / fingerprint verification), adoption through the
DeviceRunner (provenance, hand-set-wins, loud mismatch refusal, and
the bit-identity contract: an adopted plan changes wall time only),
the trial harness on a tiny workload, and trace_report --compare.
The full search loop and the composed-adversarial gate run in
scripts/determinism_gate.py --tuned (CI) and are exercised here on a
tiny budget as a slow test.
"""

import json
import os
import sys

import pytest

from shadow_tpu import simtime
from shadow_tpu.config import load_config_str
from shadow_tpu.config.schema import ExperimentalOptions
from shadow_tpu.core.controller import Controller, build
from shadow_tpu.device.runner import device_twin
from shadow_tpu.tune import plan as planmod
from shadow_tpu.tune import space

TGEN_SMALL = """
general:
  stop_time: {stop}
  seed: 3
network:
  graph:
    type: 1_gbit_switch
experimental:
  scheduler_policy: tpu
{extra}hosts:
  server:
    quantity: 2
    processes:
    - path: model:tgen_server
      start_time: 100ms
  client:
    quantity: 14
    processes:
    - path: model:tgen_client
      args: server=server size=4KiB count=3 pause=100ms
      start_time: 200ms
"""


def _cfg(stop="2s", extra=""):
    return load_config_str(TGEN_SMALL.format(stop=stop, extra=extra))


def _sig(c):
    return [(h.name, h.trace_checksum, h.events_executed,
             h.packets_sent, h.packets_dropped, h.packets_delivered)
            for h in c.sim.hosts]


# ---------------------------------------------------------------------
# schema: the shared keyword-vs-path helper across all three knobs
# ---------------------------------------------------------------------

def test_schema_strategy_plan_keyword_or_path():
    assert ExperimentalOptions.from_dict({}).strategy_plan == "off"
    for ok in ("auto", "off", "artifacts/PLAN_x.json",
               "./rel/PLAN.json"):
        assert ExperimentalOptions.from_dict(
            {"strategy_plan": ok}).strategy_plan == ok
    # YAML 1.1 bare on/off arrive as booleans
    assert ExperimentalOptions.from_dict(
        {"strategy_plan": False}).strategy_plan == "off"
    assert ExperimentalOptions.from_dict(
        {"strategy_plan": True}).strategy_plan == "auto"
    for bad in ("atuo", "on_", "plan.txt", 3, ["x"]):
        with pytest.raises(ValueError, match="strategy_plan"):
            ExperimentalOptions.from_dict({"strategy_plan": bad})


def test_schema_shared_helper_still_rejects_siblings():
    """The refactor onto one helper must keep the siblings' loud
    typo rejection (capacity_plan record paths, compile_cache dir
    paths) intact."""
    with pytest.raises(ValueError, match="capacity_plan"):
        ExperimentalOptions.from_dict(
            {"capacity_plan": "atuo", "scheduler_policy": "tpu"})
    with pytest.raises(ValueError, match="compile_cache"):
        ExperimentalOptions.from_dict({"compile_cache": "atuo"})
    with pytest.raises(ValueError, match="compile_cache"):
        ExperimentalOptions.from_dict({"compile_cache": 3})
    assert ExperimentalOptions.from_dict(
        {"compile_cache": False}).compile_cache == "off"


def test_schema_capacity_headroom():
    ok = ExperimentalOptions.from_dict(
        {"capacity_headroom": 1.25, "capacity_plan": "auto",
         "scheduler_policy": "tpu"})
    assert ok.capacity_headroom == 1.25
    with pytest.raises(ValueError, match="capacity_headroom"):
        ExperimentalOptions.from_dict(
            {"capacity_headroom": 0.5, "capacity_plan": "auto",
             "scheduler_policy": "tpu"})
    with pytest.raises(ValueError, match="capacity_headroom"):
        ExperimentalOptions.from_dict({"capacity_headroom": 1.5})


# ---------------------------------------------------------------------
# the plan space
# ---------------------------------------------------------------------

def test_space_gates_by_policy_and_mesh():
    cfg = _cfg()
    ctx = space.context(cfg, n_shards=1)
    names = [k.name for k in space.applicable(cfg, ctx)]
    assert "dispatch_segment" in names
    assert "exchange" not in names          # one shard
    assert "hybrid_judge_min_batch" not in names    # tpu policy
    assert "capacity_headroom" not in names  # capacity_plan static
    ctx8 = space.context(cfg, n_shards=8)
    assert "exchange" in [k.name for k in space.applicable(cfg, ctx8)]
    cfg.experimental.scheduler_policy = "hybrid"
    ctxh = space.context(cfg, n_shards=8)
    names_h = [k.name for k in space.applicable(cfg, ctxh)]
    assert names_h == ["hybrid_judge_min_batch"]


def test_space_candidates_and_order():
    cfg = _cfg(extra="  capacity_plan: auto\n")
    ctx = space.context(cfg, n_shards=4)
    knobs = space.applicable(cfg, ctx)
    # free runtime knobs precede reshaping ones (descent order)
    reshapes = [k.reshapes for k in knobs]
    assert reshapes == sorted(reshapes)
    seg = space.KNOB_BY_NAME["dispatch_segment"]
    cands = seg.candidates(cfg, ctx)
    assert len(cands) == len(set(cands))
    assert cands[0] == cfg.experimental.dispatch_segment
    exch = space.KNOB_BY_NAME["exchange"]
    assert set(exch.candidates(cfg, ctx)) == {
        "all_to_all", "all_gather", "two_phase"}
    assert "auto" not in exch.candidates(cfg, ctx)


def test_apply_assignment_validates():
    cfg = _cfg()
    applied = space.apply_assignment(
        cfg, {"dispatch_segment": "500000000"})
    assert applied == {"dispatch_segment": 500000000}
    assert cfg.experimental.dispatch_segment == 500000000
    with pytest.raises(ValueError, match="unknown knob"):
        space.apply_assignment(cfg, {"event_capacity": 4})
    # "auto" round-trips as a VALUE (an `exchange: auto` config's
    # baseline mirrors it) but is never a searched candidate
    assert space.apply_assignment(
        cfg, {"exchange": "auto"}) == {"exchange": "auto"}
    with pytest.raises(ValueError, match="exchange"):
        space.apply_assignment(cfg, {"exchange": "alltoall"})
    with pytest.raises(ValueError, match="dispatch_segment"):
        space.apply_assignment(cfg, {"dispatch_segment": -5})
    with pytest.raises(ValueError, match="capacity_headroom"):
        space.apply_assignment(cfg, {"capacity_headroom": 0.3})


# ---------------------------------------------------------------------
# PLAN records: path, round trip, verification
# ---------------------------------------------------------------------

def _twin(cfg):
    sim = build(cfg)
    return device_twin(sim), len(sim.hosts)


def _record(twin, n_hosts, knobs):
    return {"format": planmod.FORMAT,
            "workload": {**planmod.workload_stamp(twin, n_hosts),
                         "stop_time": 2_000_000_000, "seed": 3},
            "default": {}, "knobs": dict(knobs),
            "score": {"pkts_per_s": 1.0}}


def test_plan_path_is_fingerprint_keyed(tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    twin, H = _twin(_cfg())
    p = planmod.plan_path(twin, H)
    assert p.startswith(str(tmp_path))
    assert f"PLAN_TgenDevice_{H}_" in p and p.endswith(".json")
    # a different traffic shape fingerprints to a different file
    twin2, H2 = _twin(load_config_str(TGEN_SMALL.format(
        stop="2s", extra="").replace("count=3", "count=5")))
    assert planmod.plan_path(twin2, H2) != p


def test_plan_roundtrip_and_validation(tmp_path):
    twin, H = _twin(_cfg())
    rec = _record(twin, H, {"dispatch_segment": 250_000_000})
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(rec, path)
    back = planmod.load_plan(path)
    assert back["knobs"] == {"dispatch_segment": 250_000_000}
    planmod.verify_workload(back, twin, H)
    with pytest.raises(ValueError, match="tuned for"):
        planmod.verify_workload(back, twin, H + 1)
    bad = dict(rec, format=99)
    planmod.save_plan(bad, path)
    with pytest.raises(ValueError, match="format"):
        planmod.load_plan(path)
    (tmp_path / "PLAN_m.json").write_text(json.dumps(
        {"format": planmod.FORMAT, "knobs": {}}))
    with pytest.raises(ValueError, match="workload"):
        planmod.load_plan(str(tmp_path / "PLAN_m.json"))


def test_resolve_plan_modes(tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_OCC_DIR", str(tmp_path))
    twin, H = _twin(_cfg())
    assert planmod.resolve_plan("off", twin, H) == (None, "")
    # auto with no canonical record: silent no-op
    assert planmod.resolve_plan("auto", twin, H) == (None, "")
    # an explicit missing path is a loud error
    with pytest.raises(ValueError, match="does not exist"):
        planmod.resolve_plan(str(tmp_path / "nope.json"), twin, H)
    canon = planmod.plan_path(twin, H)
    planmod.save_plan(_record(twin, H, {"dispatch_segment": 1}),
                      canon)
    rec, path = planmod.resolve_plan("auto", twin, H)
    assert path == canon and rec["knobs"] == {"dispatch_segment": 1}


# ---------------------------------------------------------------------
# adoption through the runner: provenance + bit-identity
# ---------------------------------------------------------------------

def test_adopted_plan_is_bit_identical_with_provenance(tmp_path):
    twin, H = _twin(_cfg())
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(
        _record(twin, H, {"dispatch_segment": 500_000_000}), path)

    c_def = Controller(_cfg())
    s_def = c_def.run()
    assert s_def.ok and s_def.strategy_plan is None

    c_tuned = Controller(_cfg(
        extra=f"  strategy_plan: {path}\n"))
    s_tuned = c_tuned.run()
    assert s_tuned.ok
    assert _sig(c_tuned) == _sig(c_def)
    prov = s_tuned.strategy_plan
    assert prov["path"] == path
    assert prov["knobs"] == {"dispatch_segment": 500_000_000}
    # the knob actually reached the engine's segmentation: the
    # tuned run dispatched in more, shorter segments
    assert c_tuned.sim.cfg.experimental.dispatch_segment == \
        500_000_000


def test_adoption_refuses_fingerprint_mismatch(tmp_path):
    twin, H = _twin(_cfg())
    path = str(tmp_path / "PLAN_t.json")
    rec = _record(twin, H, {"dispatch_segment": 500_000_000})
    rec["workload"]["app_fp"] = "deadbeef0000"
    planmod.save_plan(rec, path)
    with pytest.raises(ValueError, match="tuned for"):
        Controller(_cfg(extra=f"  strategy_plan: {path}\n"))


def test_adoption_hand_set_wins_and_inapplicable_skipped(tmp_path):
    twin, H = _twin(_cfg())
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(
        _record(twin, H, {"dispatch_segment": 500_000_000,
                          "hybrid_judge_min_batch": 64}), path)
    # dispatch_segment hand-set in the config -> the plan must not
    # override it; hybrid_judge_min_batch gates on the hybrid policy
    # -> inapplicable on this tpu run
    c = Controller(_cfg(extra=("  dispatch_segment: 1s\n"
                               f"  strategy_plan: {path}\n")))
    prov = c.runner.strategy_plan
    assert prov["knobs"] == {}
    assert "hand-set" in prov["skipped"]["dispatch_segment"]
    assert "not applicable" in prov["skipped"]["hybrid_judge_min_batch"]
    assert c.sim.cfg.experimental.dispatch_segment == \
        simtime.from_seconds(1.0)


def test_adoption_on_hybrid_policy_tunes_the_judge(tmp_path):
    """The judge batching knob is the plan space's hybrid member
    (the ROADMAP's first concrete target): a hybrid-policy run must
    adopt it — through the Controller's hybrid branch, with the gate
    seeing the policy actually running — and reflect it into the
    DeviceJudge the manager consults."""
    twin, H = _twin(_cfg())
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(
        _record(twin, H, {"hybrid_judge_min_batch": 777,
                          "dispatch_segment": 500_000_000}), path)
    cfg = _cfg(extra=f"  strategy_plan: {path}\n")
    cfg.experimental.scheduler_policy = "hybrid"
    c = Controller(cfg)
    prov = c.strategy_plan
    assert prov["knobs"] == {"hybrid_judge_min_batch": 777}
    assert "not applicable" in prov["skipped"]["dispatch_segment"]
    assert c.manager.net_judge.min_batch == 777
    s = c.run()
    assert s.ok and s.strategy_plan == prov


def test_adoption_cadence_knob_uses_plan_tuned_from(tmp_path):
    """Cadence knobs only exist on configs that set them, so the
    hand-set reference is the baseline the plan was tuned FROM (its
    recorded default), not the schema zero: a config still at the
    tuned-from cadence adopts the coarsened one; a config the
    operator moved since tuning keeps its value."""
    extra = ("  checkpoint_save: {dir}/ck.npz\n"
             "  checkpoint_every: 500ms\n")
    cfg = _cfg(extra=extra.format(dir=tmp_path))
    twin, H = _twin(cfg)
    rec = _record(twin, H, {"checkpoint_every": 1_000_000_000})
    rec["default"] = {"checkpoint_every": 500_000_000}
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(rec, path)

    c = Controller(_cfg(extra=extra.format(dir=tmp_path)
                        + f"  strategy_plan: {path}\n"))
    assert c.runner.strategy_plan["knobs"] == {
        "checkpoint_every": 1_000_000_000}
    assert c.sim.cfg.experimental.checkpoint_every == 1_000_000_000

    # operator moved the cadence since tuning -> the plan loses
    moved = extra.format(dir=tmp_path).replace("500ms", "250ms")
    c2 = Controller(_cfg(extra=moved + f"  strategy_plan: {path}\n"))
    assert "hand-set" in \
        c2.runner.strategy_plan["skipped"]["checkpoint_every"]
    assert c2.sim.cfg.experimental.checkpoint_every == 250_000_000


# ---------------------------------------------------------------------
# trial harness
# ---------------------------------------------------------------------

def test_run_trial_scores_and_diagnoses(tmp_path):
    from shadow_tpu.tune.trials import run_trial

    cfg_path = str(tmp_path / "tgen_small.yaml")
    with open(cfg_path, "w") as f:
        f.write(TGEN_SMALL.format(stop="2s", extra=""))
    t = run_trial(cfg_path, {"dispatch_segment": 0},
                  window_ns=1_000_000_000)
    assert t.ok, t.error
    assert t.packets > 0 and t.pkts_per_s > 0
    assert t.signature
    # the per-phase diagnostic rides the ledger entry, and the score
    # wall excludes the one-time compile/plan costs
    assert "dispatch_s" in t.phases
    assert t.score_wall_s <= t.wall_s + 1e-6
    led = t.ledger()
    assert led["knobs"] == {"dispatch_segment": 0}
    assert led["ok"] is True
    json.dumps(led)             # JSON-able for the PLAN file

    # identical assignment, identical window -> identical signature
    # (the guard surface the searcher compares)
    t2 = run_trial(cfg_path, {"dispatch_segment": 250_000_000},
                   window_ns=1_000_000_000)
    assert t2.ok and t2.signature == t.signature


def test_run_trial_failure_is_disqualified_not_raised(tmp_path):
    from shadow_tpu.tune.trials import run_trial

    t = run_trial(str(tmp_path / "missing.yaml"), {}, 1_000)
    assert not t.ok
    assert t.error


@pytest.mark.slow
def test_tuner_search_writes_no_slower_plan(tmp_path):
    """The full search loop on a tiny budget: the returned body is a
    valid PLAN payload, every trial bit-matched the baseline, and
    the chosen assignment is never slower than the defaults by
    construction."""
    from shadow_tpu.tune.trials import Tuner

    cfg_path = str(tmp_path / "tgen_small.yaml")
    with open(cfg_path, "w") as f:
        f.write(TGEN_SMALL.format(stop="2s", extra=""))
    tuner = Tuner(cfg_path, window_ns=1_000_000_000, budget=3)
    body = tuner.search("coordinate_descent")
    assert body["policy"] == "tpu"
    assert body["space"] and body["trials"]
    assert not [t for t in body["trials"]
                if "diverged" in t.get("error", "")]
    assert set(body["knobs"]) == set(body["default"])
    if body["improved"]:
        assert body["score"]["speedup"] > 1.0
    else:
        assert body["knobs"] == body["default"]


# ---------------------------------------------------------------------
# bench provenance stamping: verified plans stamp, mismatches refuse
# ---------------------------------------------------------------------

def test_bench_plan_stamp_refuses_mismatch(tmp_path):
    """bench._plan_stamp re-verifies the PLAN file on disk against
    the run's workload fingerprint before stamping provenance — a
    mismatched (or vanished) file stamps the refusal, never the
    plan. Provenance comes from SimStats, so a tpu rung that fell
    back to hybrid (runner None) still stamps its adopted plan."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    twin, H = _twin(_cfg())
    path = str(tmp_path / "PLAN_t.json")
    planmod.save_plan(_record(twin, H, {"dispatch_segment": 7}), path)

    class FakeRunner:
        app = twin

    class FakeSim:
        hosts = [object()] * H

    class FakeC:
        runner = FakeRunner()
        sim = FakeSim()

    class FakeStats:
        strategy_plan = {"path": path,
                         "knobs": {"dispatch_segment": 7},
                         "skipped": {}, "score": None}

    stamp = bench._plan_stamp(FakeC(), FakeStats())
    assert stamp["plan"]["path"] == path
    assert stamp["plan"]["knobs"] == {"dispatch_segment": 7}

    # the hybrid-fallback shape: no runner, the twin re-derived from
    # the sim — the stamp must still carry the plan
    class HybridC:
        runner = None
        sim = None          # replaced below with a real built sim

    from shadow_tpu.core.controller import build
    HybridC.sim = build(_cfg())
    stamp = bench._plan_stamp(HybridC(), FakeStats())
    assert stamp["plan"]["path"] == path

    # corrupt the on-disk fingerprint: the stamp must flip to the
    # refusal, not carry stale provenance
    rec = _record(twin, H, {"dispatch_segment": 7})
    rec["workload"]["app_fp"] = "deadbeef0000"
    planmod.save_plan(rec, path)
    stamp = bench._plan_stamp(FakeC(), FakeStats())
    assert stamp["plan"] is None
    assert "tuned for" in stamp["plan_error"]

    os.unlink(path)
    stamp = bench._plan_stamp(FakeC(), FakeStats())
    assert stamp["plan"] is None and "plan_error" in stamp

    # no plan in play -> an explicit None stamp, never a KeyError
    class NoPlanStats:
        strategy_plan = None

    assert bench._plan_stamp(FakeC(), NoPlanStats()) == {"plan": None}


# ---------------------------------------------------------------------
# trace_report --compare
# ---------------------------------------------------------------------

def test_trace_report_compare(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import trace_report

    a = {"format": 1, "mode": "summary", "total_wall_s": 10.0,
         "phases": {"dispatch_s": 6.0, "host_s": 3.0,
                    "compile_s": 1.0},
         "dominant_phase": "dispatch", "spans": 3,
         "counters": {"packets": 1000}}
    b = {"format": 1, "mode": "summary", "total_wall_s": 5.0,
         "phases": {"dispatch_s": 1.5, "host_s": 3.0,
                    "compile_s": 0.5},
         "dominant_phase": "host", "spans": 3,
         "counters": {"packets": 1000}}
    pa, pb = tmp_path / "METRICS_a.json", tmp_path / "METRICS_b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    trace_report.print_compare(trace_report.load_metrics(str(pa)),
                               trace_report.load_metrics(str(pb)),
                               str(pa), str(pb))
    out = capsys.readouterr().out
    assert "-4.500" in out          # dispatch delta
    assert "-75.0%" in out
    assert "2.00x" in out           # pkts/s ratio
    assert "shifted" in out         # dominant phase moved
    # the total row reconciles
    assert "-5.000" in out
