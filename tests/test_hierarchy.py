"""Hierarchical (cluster-factored) topology representation.

The contract under test (docs/topology.md): under
`network.topology.representation: hierarchical` the path tables factor
into a [C,C] cluster pair + per-vertex access/self vectors whose
composed values are BIT-IDENTICAL to the dense [V,V] pipeline — at
build time, per fault epoch, through the device judge, and across
ensemble variations — or the build refuses loudly (`hierarchical` is a
hard error, `auto` falls back to dense with a log line). Full-run
trace identity across policies additionally runs in CI via
`determinism_gate.py examples/tgen_faults_hier.yaml
--policy serial,thread,tpu`.
"""

import logging

import numpy as np
import pytest

from shadow_tpu import simtime
from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller, build
from shadow_tpu.faults import FaultEvent, FaultTable, compile_link_faults
from shadow_tpu.topology import hierarchy
from shadow_tpu.topology.generate import generate_star_clusters
from shadow_tpu.topology.gml import GmlError
from shadow_tpu.topology.graph import Topology

MS = simtime.SIMTIME_ONE_MILLISECOND
S = simtime.SIMTIME_ONE_SECOND


def _clustered_gml(n_hubs=3, spokes=(2, 2, 2), hub_loss=0.01,
                   rng=None):
    """Hub clique + per-hub spokes; lossless access links (the
    reliability-exactness condition), lossy hub links. Random
    latencies when an rng is passed."""
    def lat(lo, hi):
        return int(rng.integers(lo, hi)) if rng is not None else lo
    V = n_hubs + sum(spokes)
    lines = ["graph [ directed 0"]
    for i in range(V):
        lines.append(f'  node [ id {i} bandwidth_down "1 Gbit" '
                     f'bandwidth_up "1 Gbit" ]')
    for a in range(n_hubs):
        for b in range(a + 1, n_hubs):
            lines.append(f'  edge [ source {a} target {b} latency '
                         f'"{lat(20, 90)} ms" packet_loss {hub_loss} ]')
    k = n_hubs
    for h, n in enumerate(spokes):
        for _ in range(n):
            lines.append(f'  edge [ source {h} target {k} latency '
                         f'"{lat(1, 9)} ms" packet_loss 0.0 ]')
            k += 1
    lines.append("]")
    return "\n".join(lines)


def _both(text):
    return (Topology.from_gml(text, representation="dense"),
            Topology.from_gml(text, representation="hierarchical"))


# ------------------------------------------------- build + exactness
def test_factored_matches_dense_bitwise():
    td, th = _both(_clustered_gml())
    assert th.representation == "hierarchical" and th.hier is not None
    assert td.representation == "dense" and td.hier is None
    # hierarchical drops the O(V^2) matrices entirely
    assert th.latency_ns is None and th.reliability is None
    hlat, hrel = th.hier.dense()
    np.testing.assert_array_equal(hlat, td.latency_ns)
    np.testing.assert_array_equal(hrel, td.reliability)
    assert th.min_latency_ns == td.min_latency_ns
    assert th.table_nbytes() < td.table_nbytes()
    # the scalar CPU lookup is the same composition
    V = td.n_vertices
    for sv in range(V):
        for dv in range(V):
            assert th.path(sv, dv) == td.path(sv, dv)


@pytest.mark.parametrize("seed", range(5))
def test_property_random_clustered_topologies(seed):
    rng = np.random.default_rng(seed)
    n_hubs = int(rng.integers(2, 6))
    spokes = tuple(int(rng.integers(0, 4)) for _ in range(n_hubs))
    text = _clustered_gml(n_hubs, spokes,
                          hub_loss=float(rng.choice([0.0, 0.02, 0.1])),
                          rng=rng)
    td, th = _both(text)
    hlat, hrel = th.hier.dense()
    np.testing.assert_array_equal(hlat, td.latency_ns)
    np.testing.assert_array_equal(hrel, td.reliability)
    assert th.min_latency_ns == td.min_latency_ns
    # ... and across random fault epochs on real edges of the graph
    # (vertex ids == indices here, so edge arrays name GML ids)
    events, t = [], 1 * S
    for _ in range(int(rng.integers(1, 4))):
        k = int(rng.integers(0, len(td.edge_src)))
        events.append(FaultEvent(
            kind="degrade", time=t, duration=1 * S,
            source=int(td.edge_src[k]), target=int(td.edge_dst[k]),
            latency_multiplier=float(rng.integers(2, 5))))
        t += 2 * S
    fd = compile_link_faults(td, events)
    fh = compile_link_faults(th, events)
    np.testing.assert_array_equal(fd.times, fh.times)
    for e, ht in enumerate(fh.epochs):
        dl, dr = ht.dense()
        np.testing.assert_array_equal(dl, fd.latency_ns[e])
        np.testing.assert_array_equal(dr, fd.reliability[e])


# A 2-hub / 2-spoke graph whose all-lossy reliabilities do NOT factor
# through float32 (found by search: the dense multi-hop product and
# the factored (acc*core)*acc round differently by one ulp).
NONFACTORABLE_LOSSY = """graph [ directed 0
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 2 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 3 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 1 latency "20 ms" packet_loss 0.249207 ]
  edge [ source 0 target 2 latency "2 ms" packet_loss 0.034273 ]
  edge [ source 1 target 3 latency "3 ms" packet_loss 0.429362 ]
]"""


def test_hierarchical_is_a_hard_error_when_it_cannot_reproduce_dense():
    with pytest.raises(GmlError, match="bit for bit"):
        Topology.from_gml(NONFACTORABLE_LOSSY,
                          representation="hierarchical")
    with pytest.raises(GmlError, match="does not factor"):
        # direct-edge-only routing never factors
        Topology.from_gml("""graph [ directed 0
          node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
          node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
          edge [ source 0 target 1 latency "5 ms" packet_loss 0.0 ]
          edge [ source 0 target 0 latency "2 ms" packet_loss 0.0 ]
          edge [ source 1 target 1 latency "3 ms" packet_loss 0.0 ]
        ]""", use_shortest_path=False,
            representation="hierarchical")


def test_auto_falls_back_to_dense_loudly(caplog):
    with caplog.at_level(logging.INFO):
        top = Topology.from_gml(NONFACTORABLE_LOSSY,
                                representation="auto")
    assert top.representation == "dense" and top.hier is None
    assert top.latency_ns is not None
    assert any("dense fallback" in r.message for r in caplog.records)
    # ... but picks hierarchical when the graph factors and shrinks
    top = Topology.from_gml(_clustered_gml(), representation="auto")
    assert top.representation == "hierarchical"
    # ... and dense when factoring would not shrink (no spokes)
    hubs_only = _clustered_gml(3, (0, 0, 0))
    top = Topology.from_gml(hubs_only, representation="auto")
    assert top.representation == "dense"


def test_unknown_representation_rejected():
    with pytest.raises(GmlError, match="representation"):
        Topology.from_gml(_clustered_gml(), representation="sparse")


# ------------------------------------------------------ fault epochs
FAULTS = [
    FaultEvent(kind="link_down", time=1 * S, source=0, target=1),
    FaultEvent(kind="degrade", time=2 * S, duration=1 * S, source=1,
               target=2, latency_multiplier=3.0,
               extra_packet_loss=0.25),
    FaultEvent(kind="link_up", time=4 * S, source=0, target=1),
    FaultEvent(kind="degrade", time=5 * S, duration=1 * S, source=0,
               target=3, latency_multiplier=2.0),       # access link
    FaultEvent(kind="link_down", time=7 * S, source=1, target=5),
    FaultEvent(kind="link_up", time=8 * S, source=1, target=5),
]


@pytest.fixture(scope="module")
def faulted():
    td, th = _both(_clustered_gml())
    return td, th, compile_link_faults(td, FAULTS), \
        compile_link_faults(th, FAULTS)


def test_fault_epochs_bit_identical_to_dense(faulted):
    td, th, fd, fh = faulted
    assert fh.is_hierarchical and not fd.is_hierarchical
    np.testing.assert_array_equal(fd.times, fh.times)
    V = td.n_vertices
    for t in fd.times:
        for sv in range(V):
            for dv in range(V):
                assert fd.lookup(int(t), sv, dv) == \
                    fh.lookup(int(t), sv, dv)
    # stacked device leaves materialize to the dense epoch stacks
    latp, relp = fh.lat_parts_stacked(), fh.rel_parts_stacked()
    for e in range(fh.n_epochs):
        dl, dr = hierarchy.dense_from_parts(
            tuple(p[e] for p in latp), tuple(p[e] for p in relp))
        np.testing.assert_array_equal(dl, fd.latency_ns[e])
        np.testing.assert_array_equal(dr, fd.reliability[e])
    assert fh.min_latency_ns == fd.min_latency_ns


def test_lazy_fault_table_shares_base_and_fingerprint(faulted):
    td, th, fd, fh = faulted
    # the healthy epochs REFERENCE the topology matrices — no copy
    assert fd._lat_epochs[0] is td.latency_ns
    assert fd._rel_epochs[0] is td.reliability
    # the lazy table is indistinguishable from the eager stack
    stacked = FaultTable(times=fd.times,
                         latency_ns=np.stack(fd._lat_epochs),
                         reliability=np.stack(fd._rel_epochs))
    assert fd.fingerprint() == stacked.fingerprint()
    np.testing.assert_array_equal(fd.latency_ns, stacked.latency_ns)


def test_world_tables_single_resolver(faulted):
    td, th, fd, fh = faulted
    # fault-free: dense ndarrays vs factored part tuples
    lat, rel, ept = hierarchy.world_tables(th, None)
    assert isinstance(lat, tuple) and ept is None
    dl, dr = hierarchy.dense_from_parts(lat, rel)
    np.testing.assert_array_equal(dl, td.latency_ns)
    np.testing.assert_array_equal(dr, td.reliability)
    # faulted: both resolve to the same epoch grid
    ld, rd, ed = hierarchy.world_tables(td, fd)
    lh, rh, eh = hierarchy.world_tables(th, fh)
    assert not isinstance(ld, tuple) and isinstance(lh, tuple)
    np.testing.assert_array_equal(ed, eh)
    for e in range(fh.n_epochs):
        dl, dr = hierarchy.dense_from_parts(
            tuple(p[e] for p in lh), tuple(p[e] for p in rh))
        np.testing.assert_array_equal(dl, ld[e])
        np.testing.assert_array_equal(dr, rd[e])


def test_unreachable_plus_access_change_refused():
    # downing a spoke's only edge while another access latency is
    # degraded in the same window: the dense unreachable rule (healthy
    # base latency) does not factor — the compiler must refuse with a
    # pointer at representation: dense, not silently diverge
    _, th = _both(_clustered_gml())
    events = [
        FaultEvent(kind="link_down", time=1 * S, source=1, target=5),
        FaultEvent(kind="degrade", time=1 * S, duration=2 * S,
                   source=0, target=3, latency_multiplier=2.0),
        FaultEvent(kind="link_up", time=4 * S, source=1, target=5),
    ]
    with pytest.raises(ValueError, match="representation: dense"):
        compile_link_faults(th, events)


def test_device_judge_parity_dense_vs_hier(faulted):
    from shadow_tpu.device.judge import DeviceJudge

    td, th, fd, fh = faulted
    V = td.n_vertices
    hv = np.arange(V, dtype=np.int32)
    rng = np.random.default_rng(0)
    N = 300
    now = rng.integers(0, 9 * S, N).astype(np.int64)
    src = rng.integers(0, V, N).astype(np.int32)
    dst = rng.integers(0, V, N).astype(np.int32)
    seq = np.arange(N, dtype=np.int32)
    for ft_d, ft_h in [(None, None), (fd, fh)]:
        jd = DeviceJudge(td, hv, seed=42, fault_table=ft_d)
        jh = DeviceJudge(th, hv, seed=42, fault_table=ft_h)
        deld, timd = jd.judge_batch(now, src, dst, seq)
        delh, timh = jh.judge_batch(now, src, dst, seq)
        np.testing.assert_array_equal(deld, delh)
        np.testing.assert_array_equal(timd, timh)


# ------------------------------------------------ star_clusters + stride
def test_star_clusters_layout_and_paths():
    top = generate_star_clusters(
        {"clusters": 3, "spokes_per_cluster": 2,
         "hub_latency": "10 ms", "access_latency": "2 ms"},
        representation="hierarchical")
    assert top.n_vertices == 9 and top.hier.n_clusters == 3
    # spoke k of hub h at C + h*S + k
    assert top.path(3, 4) == (2 * MS + 0 + 2 * MS, 1.0)   # same hub
    assert top.path(3, 5)[0] == 2 * MS + 10 * MS + 2 * MS  # cross hub
    assert top.path(0, 1)[0] == 10 * MS                    # hub-hub
    assert top.path(3, 0)[0] == 2 * MS                     # spoke-hub
    # bit-identical to its own dense build
    td = generate_star_clusters(
        {"clusters": 3, "spokes_per_cluster": 2,
         "hub_latency": "10 ms", "access_latency": "2 ms"})
    hlat, hrel = top.hier.dense()
    np.testing.assert_array_equal(hlat, td.latency_ns)
    np.testing.assert_array_equal(hrel, td.reliability)


def test_star_clusters_validation():
    with pytest.raises(GmlError, match="clusters"):
        generate_star_clusters({"clusters": 0})
    with pytest.raises(GmlError, match="latencies"):
        generate_star_clusters({"clusters": 2, "hub_latency": "0 ms"})
    with pytest.raises(GmlError, match="hub_packet_loss"):
        generate_star_clusters({"clusters": 2, "hub_packet_loss": 1.5})
    with pytest.raises(GmlError, match="complete"):
        generate_star_clusters({"clusters": 2},
                               use_shortest_path=False)
    # degenerate 1-vertex graph is complete and builds
    top = generate_star_clusters({"clusters": 1})
    assert top.n_vertices == 1 and top.complete


STAR_CFG = """
general: {{stop_time: 500ms, seed: 3}}
network:
  topology:
    representation: hierarchical
  graph:
    type: star_clusters
    clusters: 2
    spokes_per_cluster: 3
    hub_latency: 10 ms
    access_latency: 1 ms
experimental:
  scheduler_policy: {policy}
hosts:
  server:
    network_node_id: 2
    processes: [{{path: "model:tgen_server", start_time: 10ms}}]
  client:
    quantity: {q}
    network_node_id: 3
    network_node_stride: {stride}
    processes:
    - path: model:tgen_client
      args: server=server size=20KiB count=1 pause=50ms retry=200ms
      start_time: 50ms
"""


def test_stride_places_hosts_on_consecutive_vertices():
    sim = build(load_config_str(
        STAR_CFG.format(policy="serial", q=3, stride=1)))
    assert sim.topology.representation == "hierarchical"
    vs = {h.name: h.vertex for h in sim.hosts}
    # spokes of hub 0 are vertices 2,3,4 — server pinned at 2,
    # clients tile 3,4,5 (5 = first spoke of hub 1)
    assert vs["server"] == 2
    assert [vs[f"client{i}"] for i in range(3)] == [3, 4, 5]


def test_stride_schema_validation():
    bad = STAR_CFG.format(policy="serial", q=3, stride=1).replace(
        "    network_node_id: 3\n", "")
    with pytest.raises(ValueError, match="network_node_id"):
        load_config_str(bad)
    with pytest.raises(ValueError, match="network_node_stride"):
        load_config_str(
            STAR_CFG.format(policy="serial", q=3, stride=-1))


def test_stride_walking_past_topology_rejected():
    with pytest.raises(ValueError, match="walks past"):
        build(load_config_str(
            STAR_CFG.format(policy="serial", q=3, stride=4)))


# --------------------------------------------------------- ensemble
ENS_SCALE = """
ensemble:
  replicas: 2
  vary:
    latency_scale: [1.0, 2.0]
"""


def _star_ens_cfg(ensemble, rep="hierarchical", acc_loss=0.0):
    text = STAR_CFG.format(policy="tpu", q=3, stride=1)
    text = text.replace("representation: hierarchical",
                        f"representation: {rep}")
    text = text.replace("access_latency: 1 ms",
                        "access_latency: 1 ms\n"
                        f"    access_packet_loss: {acc_loss}")
    return load_config_str(text + ensemble)


def test_ensemble_factored_worlds_match_dense():
    from shadow_tpu.ensemble.spec import build_worlds

    wh = build_worlds(build(_star_ens_cfg(ENS_SCALE)),
                      _star_ens_cfg(ENS_SCALE).ensemble)
    cd = _star_ens_cfg(ENS_SCALE, rep="dense")
    wd = build_worlds(build(cd), cd.ensemble)
    assert isinstance(wh.latency, tuple)
    assert wh.lookahead == wd.lookahead
    for r in range(2):
        dl, dr = hierarchy.dense_from_parts(
            tuple(np.asarray(p[r], np.int64) for p in wh.latency),
            tuple(p[r] for p in wh.reliability))
        np.testing.assert_array_equal(dl, wd.latency[r])
        np.testing.assert_array_equal(dr, wd.reliability[r])


def test_ensemble_loss_delta_refused_under_lossy_access():
    from shadow_tpu.ensemble.spec import build_worlds

    ens = ENS_SCALE.replace("latency_scale: [1.0, 2.0]",
                            "packet_loss_delta: [0.0, 0.1]")
    cfg = _star_ens_cfg(ens, acc_loss=0.05)
    with pytest.raises(ValueError, match="lossless access"):
        build_worlds(build(cfg), cfg.ensemble)


# ------------------------------------- engine facts + admission bytes
@pytest.mark.slow
def test_program_facts_and_footprint_representation():
    from shadow_tpu.device import capacity

    c = Controller(_star_ens_cfg(""))
    stats = c.run()
    assert stats.ok
    pf = c.runner.engine.program_facts
    assert pf["representation"] == "hierarchical"
    assert pf["n_clusters"] == 2
    est = capacity.footprint(c.runner.engine)
    assert est["representation"] == "hierarchical"
    # the factored world prices what is actually uploaded: far below
    # even this tiny topology's 8-host dense pair, and the stamp rides
    # the admission diagnostic
    line = capacity.admission_diagnostic(est, 2**30, "config")
    assert "hierarchical tables" in line
    # the dense twin of the same run disagrees on both stamps
    cd = Controller(_star_ens_cfg("", rep="dense"))
    stats_d = cd.run()
    assert stats_d.ok
    pf_d = cd.runner.engine.program_facts
    assert pf_d["representation"] == "dense"
    assert pf_d["n_clusters"] == 0
    assert capacity.footprint(
        cd.runner.engine)["representation"] == "dense"


@pytest.mark.slow
def test_million_host_example_builds_and_fits_budget():
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import load_topology

    cfg = load_config("examples/tgen_1000000.yaml")
    top = load_topology(cfg)
    assert top.n_vertices == 1_000_200
    assert top.representation == "hierarchical"
    assert top.hier.n_clusters == 200
    # the whole point: tables fit the config's device budget where the
    # dense pair (12 bytes/vertex-pair) would be terabytes
    assert top.table_nbytes() <= \
        int(cfg.experimental.device_memory_budget)
    assert top.min_latency_ns == 1 * MS
