"""Direct unit tests for utils/counters.py and utils/pqueue.py —
previously exercised only indirectly through the engine/host suites,
so a regression in either surfaced as an opaque simulation diff."""

from shadow_tpu.utils.counters import Counter
from shadow_tpu.utils.pqueue import PriorityQueue


# ---------------------------------------------------------------- Counter
def test_counter_add_sub_get():
    c = Counter()
    assert c.get("pkts") == 0           # absent names read as zero
    c.add("pkts")
    c.add("pkts", 4)
    c.sub("pkts", 2)
    assert c.get("pkts") == 3
    c.sub("deficit", 5)                 # sub may go negative (merge
    assert c.get("deficit") == -5       # semantics need signed counts)


def test_counter_merge_accumulates_disjoint_and_shared():
    a, b = Counter(), Counter()
    a.add("syscalls", 10)
    a.add("events", 1)
    b.add("syscalls", 5)
    b.add("drops", 2)
    a.merge(b)
    assert a.as_dict() == {"syscalls": 15, "events": 1, "drops": 2}
    # merge reads, never mutates, the source
    assert b.as_dict() == {"syscalls": 5, "drops": 2}


def test_counter_as_dict_is_a_copy():
    c = Counter()
    c.add("x")
    d = c.as_dict()
    d["x"] = 99
    assert c.get("x") == 1


def test_counter_str_sorted_by_name():
    c = Counter()
    c.add("zeta", 2)
    c.add("alpha", 1)
    assert str(c) == "{alpha:1, zeta:2}"


# ----------------------------------------------------------- PriorityQueue
def test_pqueue_orders_by_key():
    q = PriorityQueue()
    for key, item in [(5, "e"), (1, "a"), (3, "c")]:
        q.push(key, item)
    assert q.peek() == (1, "a")
    assert q.peek_key() == 1
    assert [q.pop() for _ in range(3)] == [(1, "a"), (3, "c"),
                                          (5, "e")]


def test_pqueue_empty_semantics():
    q = PriorityQueue()
    assert not q
    assert len(q) == 0
    assert q.peek() is None
    assert q.peek_key() is None
    assert q.pop() is None


def test_pqueue_tuple_keys_total_order():
    """Event keys are (time, src, seq) tuples; the unique trailing seq
    makes ties impossible — the deterministic total order every engine
    relies on."""
    q = PriorityQueue()
    q.push((10, 1, 2), "b")
    q.push((10, 1, 1), "a")
    q.push((9, 99, 99), "first")
    assert q.pop() == ((9, 99, 99), "first")
    assert q.pop() == ((10, 1, 1), "a")
    assert q.pop() == ((10, 1, 2), "b")
    assert len(q) == 0


def test_pqueue_interleaved_push_pop():
    q = PriorityQueue()
    q.push(4, "d")
    q.push(2, "b")
    assert q.pop() == (2, "b")
    q.push(1, "a")
    q.push(3, "c")
    assert bool(q)
    assert [q.pop()[1] for _ in range(3)] == ["a", "c", "d"]
