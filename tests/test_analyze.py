"""shadowlint (shadow_tpu/analyze) — the three-pass static suite.

Each pass is exercised three ways: a seeded-defect fixture that MUST
be caught (a deliberately leaked closure const, an undigested traced
import, an unlocked shared-dict write), the real tree that MUST pass
clean, and the baseline round-trip (add -> suppress -> regress).
The digest test additionally pins the acceptance contract: deleting
ANY module from aotcache's code-digest list that the import walk
reaches fails the pass loudly.
"""

import os

import numpy as np
import pytest

from shadow_tpu._jax import jax, jnp
from shadow_tpu.analyze import findings as F
from shadow_tpu.analyze import concurrency as CC
from shadow_tpu.analyze import imports_audit as IA
from shadow_tpu.analyze import jaxpr_audit as JA


def _errors(found):
    return [f for f in found if f.severity == F.SEV_ERROR]


# ---------------------------------------------------------------------
# Pass 1 — jaxpr audit
# ---------------------------------------------------------------------
def test_leaked_closure_const_is_caught():
    # the seeded defect: a non-scalar, non-iota array captured by the
    # trace instead of arriving as an argument — the exact class
    # PR 6's bw_digest review fix patched by hand
    leak = jnp.asarray(np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int64))
    fn = jax.jit(lambda x: x + leak)
    closed = fn.trace(
        jax.ShapeDtypeStruct((8,), np.int64)).jaxpr
    found = JA.audit_closed_jaxpr(closed, program="fixture:leak")
    assert [f.code for f in found] == ["SL101"]
    assert "wrld" in found[0].message
    assert "audit_consts" in found[0].hint


def test_benign_and_allowed_consts_pass():
    iota = jnp.asarray(np.arange(8, dtype=np.int64) * 3 + 1)
    fill = jnp.asarray(np.full(8, 7, np.int64))
    table = jnp.asarray(np.array([9, 1, 8, 2], np.int64))
    fn = jax.jit(lambda x: x + iota + fill + table[x % 4])
    closed = fn.trace(
        jax.ShapeDtypeStruct((8,), np.int64)).jaxpr
    found = JA.audit_closed_jaxpr(
        closed, program="fixture:allowed",
        allowed_consts={"table": np.array([9, 1, 8, 2], np.int64)})
    assert found == []


def test_const_classifier():
    assert JA.classify_const(np.int64(3)) == "scalar"
    assert JA.classify_const(np.full(5, 2.0)) == "fill"
    assert JA.classify_const(np.arange(6) * 7 - 2) == "iota"
    assert JA.classify_const(np.array([1, 2, 2, 1])) == "opaque"
    # a 2-element pair is NOT trivially 'affine' — it is data
    assert JA.classify_const(np.array([7, 12345])) == "opaque"
    # i64 values past 2^53 must not alias through float64 diffs
    big = np.array([0, 2 ** 60, 2 ** 61 + 1], np.int64)
    assert JA.classify_const(big) == "opaque"


def test_unpinned_primitive_is_caught(monkeypatch):
    monkeypatch.setattr(
        JA, "PRIMITIVE_ALLOWLIST",
        JA.PRIMITIVE_ALLOWLIST - {"sort"})
    fn = jax.jit(lambda x: jnp.sort(x))
    closed = fn.trace(
        jax.ShapeDtypeStruct((8,), np.int64)).jaxpr
    found = JA.audit_closed_jaxpr(closed, program="fixture:prim")
    assert any(f.code == "SL102" and f.obj == "sort" for f in found)


def _small_engine(**kw):
    return JA._build_engine(**kw)


def test_real_engine_programs_pass_clean():
    # the current engine must audit clean (post satellite fixes):
    # every program, consts + primitives + collectives
    import shadow_tpu.device.engine as engine_mod

    ok = JA.const_ok_targets(engine_mod.__file__)
    for label, eng in (
            ("base", _small_engine()),
            ("two_phase", _small_engine(exchange="two_phase")),
            ("mb", _small_engine(model_bandwidth=True))):
        found = JA.audit_engine(eng, label, ok_targets=ok)
        assert found == [], [f.format() for f in found]


def test_collective_registry_violations_flagged():
    eng = _small_engine()
    if eng.n_shards <= 1:
        pytest.skip("needs the forced multi-device mesh")
    jit_fn, args = eng.lowerable_programs()["flush"]
    closed = jit_fn.trace(*args).jaxpr
    # wrong capacity pin: the real CAP is not 999
    bad = {"axis_index": {"axis": "hosts", "caps": None},
           "all_gather": {"axis": "hosts", "caps": None},
           "all_to_all": {"axis": "hosts", "caps": (999,)}}
    found = JA.audit_closed_jaxpr(closed, program="fixture:caps",
                                  registry=bad)
    assert any(f.code == "SL103" and "dim=" in f.obj for f in found)
    # unregistered collective primitive
    none = {"axis_index": {"axis": "hosts", "caps": None}}
    found = JA.audit_closed_jaxpr(closed, program="fixture:unreg",
                                  registry=none)
    assert any(f.code == "SL103" and f.obj == "all_to_all"
               for f in found)
    # registered mover that never lowers
    ghost = {"axis_index": {"axis": "hosts", "caps": None},
             "all_gather": {"axis": "hosts", "caps": None},
             "all_to_all": {"axis": "hosts", "caps": None},
             "ppermute": {"axis": "hosts", "caps": None},
             "__expect_mover__": "ppermute"}
    found = JA.audit_closed_jaxpr(closed, program="fixture:ghost",
                                  registry=ghost)
    assert any(f.code == "SL104" and f.obj == "ppermute"
               for f in found)


def test_collective_registry_matches_effective():
    # the static registry derives from the same resolved config as
    # effective{} — the consistency the gate pins per-config
    eng = _small_engine(exchange="two_phase")
    if eng.n_shards <= 1:
        pytest.skip("needs the forced multi-device mesh")
    reg = eng.collective_registry()
    eff = eng.effective
    assert reg["ppermute"]["caps"] == (eff["CAP"], eff["CAP2"])
    eng2 = _small_engine()
    assert eng2.collective_registry()["all_to_all"]["caps"] == \
        (eng2.effective["CAP"],)


def test_const_ok_comment_enforced():
    # every audit_consts entry with a declared capture site must have
    # its # shadowlint: const-ok(...) comment in engine.py
    import shadow_tpu.device.engine as engine_mod

    ok = JA.const_ok_targets(engine_mod.__file__)
    assert {"law_t", "bw_up_t", "bw_down_t"} <= ok
    # strip the comment coverage -> the MB engine's LAW capture must
    # trip SL105
    eng = _small_engine(model_bandwidth=True)
    jit_fn, args = eng.lowerable_programs()["run"]
    closed = jit_fn.trace(*args).jaxpr
    found = JA.audit_closed_jaxpr(
        closed, program="fixture:no-comment",
        allowed_consts=eng.audit_consts(), ok_targets=set())
    assert any(f.code == "SL105" and f.obj == "model_nic.LAW"
               for f in found)


def test_bw_and_app_arrays_are_fingerprint_covered():
    # the suppression contract behind audit_consts: every allowed
    # baked array must flip the AOT cache key when its bytes change
    from shadow_tpu.device import aotcache
    from shadow_tpu.device.capacity import app_fingerprint

    eng = _small_engine(model_bandwidth=True)
    k1 = aotcache.program_key(eng, "run")
    sig = aotcache.program_signature(eng, "run")
    assert "bw_digest" in sig
    eng.bw_up = eng.bw_up.copy()
    eng.bw_up[0] += 1
    assert aotcache.program_key(eng, "run") != k1

    # app parameter arrays are hashed by app_fingerprint — the same
    # selection rule audit_consts uses (vars(app) ndarrays), so the
    # allowance is covered by construction
    from shadow_tpu.device.apps import TgenDevice

    app = TgenDevice(roles=np.array([0, 1, 1, 1], np.int32),
                     server_gid=np.zeros(4, np.int32),
                     count=np.array([1, 2, 3, 4], np.int32))
    fp1 = app_fingerprint(app)
    for name in ("_count", "_pause", "_retry", "roles"):
        assert isinstance(vars(app)[name], np.ndarray)
    app._count = np.array([1, 2, 3, 5], np.int32)
    assert app_fingerprint(app) != fp1


def test_state_structs_match_init_state():
    # the abstract mirror must not drift from the real state (the
    # audit would otherwise trace a program variant that is never
    # dispatched): shapes AND dtypes, across the optional leaves
    for eng in (_small_engine(),
                _small_engine(model_bandwidth=True, audit=True,
                              count_paths=True)):
        real = eng.init_state(
            [(i, 0, 10_000_000)
             for i in range(eng.config.n_hosts)])
        mirror = eng.state_structs()
        assert set(real) == set(mirror)
        for k, v in real.items():
            assert (tuple(v.shape), np.dtype(v.dtype)) == \
                (tuple(mirror[k].shape), np.dtype(mirror[k].dtype)), k
        wr = eng.world()
        wm = eng.world_structs()
        for a, b in zip(wr, wm):
            assert (tuple(np.asarray(a).shape),
                    np.asarray(a).dtype) == \
                (tuple(b.shape), np.dtype(b.dtype))

    ens_eng = _small_engine(ensemble=JA._tiny_ensemble())
    real = ens_eng.init_ensemble_state(
        [(i, 0, 10_000_000) for i in range(8)])
    _, args = ens_eng.lowerable_programs()["run_ens"]
    mirror = args[0]
    assert set(real) == set(mirror)
    for k, v in real.items():
        assert (tuple(v.shape), np.dtype(v.dtype)) == \
            (tuple(mirror[k].shape), np.dtype(mirror[k].dtype)), k
    for a, b in zip(ens_eng.ensemble_worlds_device(),
                    ens_eng.world_structs(ensemble=True)):
        assert (tuple(np.asarray(a).shape), np.asarray(a).dtype) == \
            (tuple(b.shape), np.dtype(b.dtype))


# ---------------------------------------------------------------------
# Pass 2 — fingerprint completeness
# ---------------------------------------------------------------------
FIXPKG = {
    "__init__.py": "",
    "engine.py": ("import fixpkg.helper\n"
                  "from fixpkg import boundary\n"
                  "def f():\n"
                  "    from fixpkg.late import g\n"
                  "    return g\n"),
    "helper.py": "X = 1\n",
    "boundary.py": "import fixpkg.hidden\n",
    "hidden.py": "",
    "late.py": "def g():\n    return 0\n",
    "stale.py": "",
}


def _fixtree(tmp_path):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    for name, src in FIXPKG.items():
        (pkg / name).write_text(src)
    return {"fixpkg": str(pkg)}


def _ia_run(pkg_roots, digest, boundary=()):
    return IA.run(
        roots=("fixpkg.engine",),
        boundary={b: "fixture boundary" for b in boundary}
        if not isinstance(boundary, dict) else boundary,
        digest=digest, pkg_roots=pkg_roots, rel_prefix="fixture")


def test_undigested_traced_import_is_caught(tmp_path):
    roots = _fixtree(tmp_path)
    # helper.py and the FUNCTION-LEVEL late.py import both reach the
    # walk; leaving either out of the digest is the seeded defect
    full = ["fixpkg.engine", "fixpkg.helper", "fixpkg.late",
            "fixpkg.boundary", "fixpkg.hidden", "fixpkg"]
    found = _ia_run(roots, digest=full)
    assert found == [], [f.format() for f in found]
    for missing in ("fixpkg.helper", "fixpkg.late"):
        found = _ia_run(roots,
                        digest=[m for m in full if m != missing])
        assert [f.code for f in _errors(found)] == ["SL201"]
        assert _errors(found)[0].obj == missing


def test_boundary_prunes_and_conflicts(tmp_path):
    roots = _fixtree(tmp_path)
    # boundary.py declared a value boundary: its own import of
    # hidden.py must NOT be followed, and neither needs digesting
    digest = ["fixpkg.engine", "fixpkg.helper", "fixpkg.late",
              "fixpkg"]
    found = _ia_run(roots, digest=digest,
                    boundary=("fixpkg.boundary",))
    assert found == [], [f.format() for f in found]
    # declaring AND digesting the same module is a contradiction
    found = _ia_run(roots, digest=digest + ["fixpkg.boundary"],
                    boundary=("fixpkg.boundary",))
    assert any(f.code == "SL203" for f in found)
    # a digested module the walk never reaches is stale (warning)
    found = _ia_run(roots, digest=digest + ["fixpkg.stale"],
                    boundary=("fixpkg.boundary",))
    stale = [f for f in found if f.code == "SL202"]
    assert len(stale) == 1 and stale[0].severity == F.SEV_WARNING
    assert not _errors(found)


def test_real_digest_walk_clean():
    assert IA.run() == []


def test_deleting_any_digested_module_fails():
    # the acceptance pin: every module in the shipped digest list is
    # load-bearing — deleting it makes the analyze rung fail
    from shadow_tpu.device import aotcache

    for mod in aotcache.CODE_DIGEST_MODULES:
        digest = [m for m in aotcache.CODE_DIGEST_MODULES
                  if m != mod]
        found = IA.run(digest=digest)
        assert any(f.code == "SL201" and f.obj == mod
                   for f in _errors(found)), mod


# ---------------------------------------------------------------------
# Pass 3 — concurrency lint
# ---------------------------------------------------------------------
FIX_SRC = '''
import threading

SHARED = {}
ANNOTATED: dict = {}

class M:
    def __init__(self):
        self._streams = {}
        self._streams_lock = threading.Lock()
        def late(k, v):
            self._streams[k] = v
        self.late = late
        self.later = lambda k: self._streams.pop(k)

    def locked_write(self, k, v):
        with self._streams_lock:
            self._streams[k] = v

    def unlocked_write(self, k, v):
        self._streams[k] = v

    def unlocked_mutator(self, k):
        return self._streams.pop(k, None)

    def suppressed(self, k):
        del self._streams[k]  # shadowlint: unlocked-ok(test only)

    def module_write(self, k):
        SHARED[k] = 1

    def annotated_write(self, k):
        ANNOTATED[k] = 1

SHARED["import-time"] = 0
'''


def test_unlocked_shared_dict_write_is_caught():
    reg = {"self._streams": "self._streams_lock"}
    sup = []
    found = CC.lint_source(FIX_SRC, "fixture.py", registry=reg,
                           suppressed_out=sup)
    by_obj = {f.obj: f for f in found}
    # the seeded defects
    assert "self._streams@unlocked_write" in by_obj
    assert "self._streams@unlocked_mutator" in by_obj
    assert by_obj["self._streams@unlocked_write"].code == "SL301"
    # the generic module-level rule (function body write; the
    # import-time population two lines later stays legal), incl.
    # PEP 526-annotated module mutables
    assert by_obj["SHARED@module_write"].code == "SL302"
    assert by_obj["ANNOTATED@annotated_write"].code == "SL302"
    # a nested def / lambda DEFINED in __init__ runs later on
    # whatever thread calls it — no construction-site exemption
    assert "self._streams@late" in by_obj
    assert "self._streams@<lambda>" in by_obj
    # direct __init__ writes and locked writes are fine; the
    # suppressed delete is absent but carries its reason out
    assert not any(o.endswith("@locked_write") or "__init__" in o
                   or "suppressed" in o for o in by_obj)
    assert len(found) == 6
    assert sup == [{"path": "fixture.py", "line": 27,
                    "reason": "test only"}]


def test_real_tree_concurrency_clean():
    assert CC.run() == [], \
        [f.format() for f in CC.run()]


def test_registry_lock_names_verified(tmp_path, monkeypatch):
    # a registry entry whose lock never appears in the file is itself
    # flagged — the registry cannot drift from the code silently
    repo = tmp_path / "repo"
    (repo / "shadow_tpu" / "core").mkdir(parents=True)
    (repo / "shadow_tpu" / "core" / "manager.py").write_text(
        "x = 1\n")
    monkeypatch.setattr(CC, "LOCK_REGISTRY", {
        "shadow_tpu/core/manager.py":
            {"self._streams": "self._ghost_lock"}})
    monkeypatch.setattr(CC, "SCAN_GLOBS",
                        ("shadow_tpu/core/manager.py",))
    found = CC.run(repo_root=str(repo))
    assert any(f.code == "SL301" and f.obj == "self._ghost_lock"
               for f in found)


# ---------------------------------------------------------------------
# findings + baseline round-trip
# ---------------------------------------------------------------------
def test_baseline_round_trip(tmp_path):
    base = str(tmp_path / "baseline.json")
    f1 = F.Finding(code="SL301", severity=F.SEV_ERROR,
                   path="a.py", obj="self.x@f", line=3,
                   message="unlocked write")
    f2 = F.Finding(code="SL201", severity=F.SEV_ERROR,
                   path="aotcache", obj="pkg.mod",
                   message="undigested")

    # add: both findings are new against the empty baseline
    new, sup, stale = F.apply_baseline([f1, f2], F.load_baseline(
        str(tmp_path / "missing.json")))
    assert len(new) == 2 and not sup and not stale

    # suppress: grandfather them, both now suppressed with reasons
    F.write_baseline(base, [f1, f2], reason="staged in PR 10")
    new, sup, stale = F.apply_baseline([f1, f2], F.load_baseline(base))
    assert not new and len(sup) == 2 and not stale
    assert all(s["reason"] == "staged in PR 10" for s in sup)

    # regress: f2 is fixed -> its suppression reads stale; a NEW
    # finding at a different site still fails
    f3 = F.Finding(code="SL301", severity=F.SEV_ERROR,
                   path="b.py", obj="self.y@g",
                   message="fresh bug")
    new, sup, stale = F.apply_baseline([f1, f3], F.load_baseline(base))
    assert [f.key for f in new] == [f3.key]
    assert len(sup) == 1 and len(stale) == 1
    assert stale[0]["key"] == f2.key

    # line drift must NOT invalidate a suppression
    f1_moved = F.Finding(code="SL301", severity=F.SEV_ERROR,
                         path="a.py", obj="self.x@f", line=99,
                         message="unlocked write")
    new, sup, _ = F.apply_baseline([f1_moved], F.load_baseline(base))
    assert not new and len(sup) == 1


def test_baseline_rejects_reasonless_and_malformed(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text('{"version": 1, "suppressions": [{"key": "x"}]}')
    with pytest.raises(ValueError, match="reason"):
        F.load_baseline(str(bad))
    bad.write_text('["not", "a", "dict"]')
    with pytest.raises(ValueError):
        F.load_baseline(str(bad))


def test_record_shape():
    f1 = F.Finding(code="SL101", severity=F.SEV_ERROR, path="p",
                   obj="o", message="m")
    rec = F.record([f1], [f1], [], [], ["jaxpr"],
                   {"jaxpr": 1.234})
    assert rec["ok"] is False
    assert rec["counts"]["new_errors"] == 1
    assert rec["findings"][0]["key"] == "SL101:p:o"
    rec = F.record([], [], [], [], ["jaxpr"], {})
    assert rec["ok"] is True


def test_subset_run_does_not_flag_other_passes_stale(tmp_path):
    # a --pass subset run cannot judge the other passes' suppressions
    # stale (their findings were never computed)
    import subprocess
    import sys

    base = tmp_path / "baseline.json"
    f_jaxpr = F.Finding(code="SL101", severity=F.SEV_ERROR,
                        path="engine[x]:run", obj="const[8]:int64:ab",
                        message="leak")
    F.write_baseline(str(base), [f_jaxpr], reason="fork staging")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable,
           os.path.join(repo, "scripts", "analyze.py"),
           "--baseline", str(base), "--strict-baseline",
           "--pass", "digest", "--pass", "concurrency"]
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=180,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stdout + p.stderr
    assert "stale suppression:" not in p.stdout
    assert "0 stale" in p.stdout


def test_shipped_baseline_is_valid_and_empty():
    data = F.load_baseline()
    assert data["suppressions"] == []


def test_unknown_pass_rejected():
    from shadow_tpu import analyze

    with pytest.raises(ValueError, match="unknown pass"):
        analyze.run_pass("nope")


# ---------------------------------------------------------------------
# the full matrix + driver (slow: builds every engine variant)
# ---------------------------------------------------------------------
@pytest.mark.slow
def test_full_jaxpr_matrix_clean():
    found = JA.run()
    assert _errors(found) == [], [f.format() for f in found]


@pytest.mark.slow
def test_analyze_driver_end_to_end(tmp_path):
    import json
    import subprocess
    import sys

    out = tmp_path / "findings.json"
    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "analyze.py"),
         "--json", str(out), "--strict-baseline"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stdout + p.stderr
    rec = json.loads(out.read_text())
    assert rec["ok"] is True
    assert set(rec["passes"]) == {"jaxpr", "digest", "concurrency"}
